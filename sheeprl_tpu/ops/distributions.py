"""Distributions as frozen pytree dataclasses — constructible inside `jit`.

JAX-native replacements for the reference's torch.distributions usage and
custom classes (/root/reference/sheeprl/utils/distribution.py): Normal,
Independent, tanh-squashed Normal (SAC), Categorical / one-hot categorical
with straight-through gradients and unimix (Dreamer), truncated normal
(DreamerV1), and the DreamerV3 trio Symlog / MSE / TwoHotEncoding.

Everything is pure: `sample(key)` threads explicit PRNG keys and is
reparameterized wherever the reference's `rsample` was.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..nn.core import Module, static
from .math import symexp, symlog, two_hot

_LOG_SQRT_2PI = 0.5 * math.log(2 * math.pi)
_LOG_SQRT_2PI_E = 0.5 * math.log(2 * math.pi * math.e)


def _sum_last(x: jax.Array, ndims: int) -> jax.Array:
    if ndims == 0:
        return x
    return x.sum(axis=tuple(range(-ndims, 0)))


class Distribution(Module):
    """Base marker class; subclasses are pytrees (array fields = leaves)."""


# ---------------------------------------------------------------------------
# Gaussian family
# ---------------------------------------------------------------------------


class Normal(Distribution):
    loc: jax.Array
    scale: jax.Array

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        shape = sample_shape + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        eps = jax.random.normal(key, shape, dtype=jnp.result_type(self.loc))
        return self.loc + self.scale * eps

    def log_prob(self, x):
        z = (x - self.loc) / self.scale
        return -0.5 * jnp.square(z) - jnp.log(self.scale) - _LOG_SQRT_2PI

    def entropy(self):
        return _LOG_SQRT_2PI_E + jnp.log(self.scale) * jnp.ones_like(self.loc)

    @property
    def mean(self):
        return self.loc

    @property
    def mode(self):
        return self.loc

    @property
    def stddev(self):
        return self.scale * jnp.ones_like(self.loc)


class Independent(Distribution):
    """Reinterpret the trailing `event_ndims` batch dims as event dims."""

    base: Distribution
    event_ndims: int = static(default=1)

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        return self.base.sample(key, sample_shape)

    def log_prob(self, x):
        return _sum_last(self.base.log_prob(x), self.event_ndims)

    def entropy(self):
        return _sum_last(self.base.entropy(), self.event_ndims)

    @property
    def mean(self):
        return self.base.mean

    @property
    def mode(self):
        return self.base.mode


class TanhNormal(Distribution):
    """tanh(Normal) with the analytic log-det-Jacobian correction — the SAC
    actor distribution (/root/reference/sheeprl/algos/sac/agent.py:102-134).
    Event dim is the trailing axis (log_probs summed over it)."""

    loc: jax.Array
    scale: jax.Array

    def sample_and_log_prob(self, key, sample_shape: tuple[int, ...] = ()):
        shape = sample_shape + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        u = self.loc + self.scale * jax.random.normal(key, shape, jnp.result_type(self.loc))
        a = jnp.tanh(u)
        base_lp = -0.5 * jnp.square((u - self.loc) / self.scale) - jnp.log(self.scale) - _LOG_SQRT_2PI
        # log(1 - tanh(u)^2) = 2 * (log 2 - u - softplus(-2u)), numerically stable
        correction = 2.0 * (math.log(2.0) - u - jax.nn.softplus(-2.0 * u))
        log_prob = (base_lp - correction).sum(axis=-1)
        return a, log_prob

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        return self.sample_and_log_prob(key, sample_shape)[0]

    def log_prob(self, value):
        # f32 throughout: in bf16 the clip bound 1 - 1e-6 rounds to exactly
        # 1.0 and arctanh(1.0) = inf would poison the loss
        value = value.astype(jnp.float32)
        eps = 1e-6
        u = jnp.arctanh(jnp.clip(value, -1.0 + eps, 1.0 - eps))
        base_lp = (
            -0.5 * jnp.square((u - self.loc) / self.scale)
            - jnp.log(self.scale)
            - _LOG_SQRT_2PI
        )
        correction = 2.0 * (math.log(2.0) - u - jax.nn.softplus(-2.0 * u))
        return (base_lp - correction).sum(axis=-1)

    @property
    def mode(self):
        return jnp.tanh(self.loc)

    @property
    def mean(self):
        return jnp.tanh(self.loc)


class TruncatedStandardNormal(Distribution):
    """Standard normal truncated to [a, b]
    (/root/reference/sheeprl/utils/distribution.py:22-110)."""

    a: jax.Array
    b: jax.Array

    @staticmethod
    def _little_phi(x):
        return jnp.exp(-0.5 * jnp.square(x)) / math.sqrt(2 * math.pi)

    @staticmethod
    def _big_phi(x):
        return 0.5 * (1.0 + jax.lax.erf(x / math.sqrt(2.0)))

    @staticmethod
    def _inv_big_phi(x):
        return math.sqrt(2.0) * jax.lax.erf_inv(2.0 * x - 1.0)

    def _z(self):
        eps = jnp.finfo(jnp.float32).eps
        return jnp.maximum(self._big_phi(self.b) - self._big_phi(self.a), eps)

    def log_prob(self, x):
        return -_LOG_SQRT_2PI - jnp.log(self._z()) - 0.5 * jnp.square(x)

    def cdf(self, x):
        return jnp.clip((self._big_phi(x) - self._big_phi(self.a)) / self._z(), 0.0, 1.0)

    def icdf(self, p):
        return self._inv_big_phi(self._big_phi(self.a) + p * self._z())

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        eps = jnp.finfo(jnp.float32).eps
        shape = sample_shape + jnp.broadcast_shapes(self.a.shape, self.b.shape)
        p = jax.random.uniform(key, shape, minval=eps, maxval=1.0 - eps)
        return self.icdf(p)

    def entropy(self):
        z = self._z()
        phi_a, phi_b = self._little_phi(self.a), self._little_phi(self.b)
        lpbb = (phi_b * self.b - phi_a * self.a) / z
        return _LOG_SQRT_2PI_E + jnp.log(z) - 0.5 * lpbb

    @property
    def mean(self):
        return -(self._little_phi(self.b) - self._little_phi(self.a)) / self._z()


class TruncatedNormal(Distribution):
    """Normal(loc, scale) truncated to [low, high]
    (/root/reference/sheeprl/utils/distribution.py:113-144)."""

    loc: jax.Array
    scale: jax.Array
    low: jax.Array
    high: jax.Array

    def _std(self) -> TruncatedStandardNormal:
        return TruncatedStandardNormal(
            a=(self.low - self.loc) / self.scale, b=(self.high - self.loc) / self.scale
        )

    def log_prob(self, x):
        return self._std().log_prob((x - self.loc) / self.scale) - jnp.log(self.scale)

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        return self._std().sample(key, sample_shape) * self.scale + self.loc

    def entropy(self):
        return self._std().entropy() + jnp.log(self.scale)

    @property
    def mean(self):
        return self._std().mean * self.scale + self.loc

    @property
    def mode(self):
        return jnp.clip(self.loc, self.low, self.high)


# ---------------------------------------------------------------------------
# Categorical family
# ---------------------------------------------------------------------------


class Categorical(Distribution):
    """Categorical over the trailing axis. Accepts unnormalized logits:
    log_prob/entropy normalize internally (log_softmax is idempotent, so
    pre-normalized logits are fine too)."""

    logits: jax.Array

    @classmethod
    def from_logits(cls, logits):
        return cls(logits=logits)

    @property
    def log_probs(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        shape = sample_shape + self.logits.shape[:-1]
        return jax.random.categorical(key, self.logits, shape=shape)

    def log_prob(self, x):
        return jnp.take_along_axis(
            self.log_probs, x[..., None].astype(jnp.int32), axis=-1
        )[..., 0]

    def entropy(self):
        lp = self.log_probs
        return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

    @property
    def mode(self):
        return jnp.argmax(self.logits, axis=-1)


class OneHotCategorical(Distribution):
    """One-hot categorical; `StraightThrough` sampling passes gradients to the
    probabilities (Dreamer stochastic state,
    /root/reference/sheeprl/algos/dreamer_v2/utils.py:21-38). Accepts
    unnormalized logits (normalized internally where it matters)."""

    logits: jax.Array

    @classmethod
    def from_logits(cls, logits):
        return cls(logits=logits)

    @property
    def log_probs(self):
        return jax.nn.log_softmax(self.logits, axis=-1)

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        idx = jax.random.categorical(
            key, self.logits, shape=sample_shape + self.logits.shape[:-1]
        )
        return jax.nn.one_hot(idx, self.logits.shape[-1], dtype=self.logits.dtype)

    def rsample(self, key, sample_shape: tuple[int, ...] = ()):
        """Straight-through gradient sample: forward = one-hot draw,
        backward = d/d(probs)."""
        sample = self.sample(key, sample_shape)
        probs = self.probs
        return sample + probs - jax.lax.stop_gradient(probs)

    def log_prob(self, x):
        return jnp.sum(self.log_probs * x, axis=-1)

    def entropy(self):
        lp = self.log_probs
        return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

    @property
    def mode(self):
        return jax.nn.one_hot(
            jnp.argmax(self.logits, axis=-1), self.logits.shape[-1], dtype=self.logits.dtype
        )


def unimix_logits(logits: jax.Array, unimix: float = 0.01) -> jax.Array:
    """Mix categorical probs with `unimix` uniform mass and return new logits
    (DreamerV3's 1% unimix, /root/reference/sheeprl/algos/dreamer_v3/agent.py:384-396)."""
    if unimix <= 0.0:
        return logits
    probs = jax.nn.softmax(logits, axis=-1)
    uniform = jnp.ones_like(probs) / probs.shape[-1]
    probs = (1.0 - unimix) * probs + unimix * uniform
    return jnp.log(probs)


class Bernoulli(Distribution):
    """Bernoulli from logits; `mode` is the safe >0.5 threshold (the continue
    head's BernoulliSafeMode in the reference)."""

    logits: jax.Array

    @property
    def probs(self):
        return jax.nn.sigmoid(self.logits)

    def sample(self, key, sample_shape: tuple[int, ...] = ()):
        shape = sample_shape + self.logits.shape
        return jax.random.bernoulli(key, self.probs, shape).astype(jnp.float32)

    def log_prob(self, x):
        # -BCE-with-logits, numerically stable
        return -(jax.nn.softplus(-self.logits) * x + jax.nn.softplus(self.logits) * (1.0 - x))

    def entropy(self):
        p = self.probs
        return jax.nn.softplus(self.logits) - self.logits * p

    @property
    def mode(self):
        return (self.probs > 0.5).astype(jnp.float32)

    @property
    def mean(self):
        return self.probs


# ---------------------------------------------------------------------------
# DreamerV3 trio
# ---------------------------------------------------------------------------


class SymlogDistribution(Distribution):
    """MSE (or L1) in symlog space
    (/root/reference/sheeprl/utils/distribution.py:148-189)."""

    _mode: jax.Array
    dims: int = static(default=1)
    dist: str = static(default="mse")
    agg: str = static(default="sum")
    tol: float = static(default=1e-8)

    def log_prob(self, value):
        if self.dist == "mse":
            distance = jnp.square(self._mode - symlog(value))
        elif self.dist == "abs":
            distance = jnp.abs(self._mode - symlog(value))
        else:
            raise NotImplementedError(self.dist)
        distance = jnp.where(distance < self.tol, 0.0, distance)
        if self.agg == "mean":
            loss = distance.mean(axis=tuple(range(-self.dims, 0)))
        else:
            loss = _sum_last(distance, self.dims)
        return -loss

    @property
    def mode(self):
        return symexp(self._mode)

    @property
    def mean(self):
        return symexp(self._mode)


class MSEDistribution(Distribution):
    """Plain MSE pseudo-likelihood
    (/root/reference/sheeprl/utils/distribution.py:192-217)."""

    _mode: jax.Array
    dims: int = static(default=1)
    agg: str = static(default="sum")

    def log_prob(self, value):
        distance = jnp.square(self._mode - value)
        if self.agg == "mean":
            loss = distance.mean(axis=tuple(range(-self.dims, 0)))
        else:
            loss = _sum_last(distance, self.dims)
        return -loss

    @property
    def mode(self):
        return self._mode

    @property
    def mean(self):
        return self._mode


class TwoHotEncodingDistribution(Distribution):
    """255-bin two-hot over symlog values — DreamerV3's reward/critic heads
    (/root/reference/sheeprl/utils/distribution.py:220-266). `log_prob(x)`
    cross-entropies a two-hot target against the logits; mean/mode decode via
    symexp(probs . bins)."""

    logits: jax.Array
    dims: int = static(default=1)
    low: float = static(default=-20.0)
    high: float = static(default=20.0)

    @property
    def bins(self):
        return jnp.linspace(self.low, self.high, self.logits.shape[-1])

    @property
    def probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    @property
    def mean(self):
        # keepdim so the event shape stays (..., 1) like the reference
        val = jnp.sum(self.probs * self.bins, axis=-1, keepdims=True)
        if self.dims > 1:
            val = _sum_last(val[..., 0], self.dims - 1)[..., None]
        return symexp(val)

    @property
    def mode(self):
        return self.mean

    def log_prob(self, x):
        # x: [..., 1] raw-scale targets
        from .pallas_kernels import two_hot_log_prob, use_pallas

        if use_pallas("two_hot"):
            k = self.logits.shape[-1]
            lp = two_hot_log_prob(
                symlog(x).reshape(-1, 1).astype(jnp.float32),
                self.logits.reshape(-1, k),
                self.bins[None],
            ).reshape(x.shape[:-1] + (1,))
            return _sum_last(lp, self.dims)
        target = two_hot(symlog(x)[..., 0], self.bins)
        log_pred = jax.nn.log_softmax(self.logits, axis=-1)
        return _sum_last((target * log_pred).sum(axis=-1)[..., None], self.dims)


# ---------------------------------------------------------------------------
# KL divergences (Dreamer KL balancing)
# ---------------------------------------------------------------------------


def kl_categorical(p_logits: jax.Array, q_logits: jax.Array, event_ndims: int = 1):
    """KL(p || q) between categoricals over the trailing axis, then summed over
    `event_ndims` trailing batch dims (the 32x32 discrete latent)."""
    p_log = jax.nn.log_softmax(p_logits, axis=-1)
    q_log = jax.nn.log_softmax(q_logits, axis=-1)
    kl = jnp.sum(jnp.exp(p_log) * (p_log - q_log), axis=-1)
    return _sum_last(kl, event_ndims)


def kl_normal(p: Normal, q: Normal, event_ndims: int = 1):
    """KL(p || q) between diagonal Gaussians, summed over trailing event dims."""
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    kl = 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))
    return _sum_last(kl, event_ndims)
