"""Pallas TPU kernels for the framework's hot ops.

The BASELINE.md north star names four kernel targets: the LayerNorm-GRU cell
(the RSSM scan body, reference /root/reference/sheeprl/models/models.py:330-402),
symlog/symexp (reference utils/utils.py:125-133), the two-hot log-prob
(reference utils/distribution.py:220-266), and the CNN encoder/decoder
stages (ops/pallas_cnn.py — fused conv/deconv + LayerNorm + SiLU,
per-family switch SHEEPRL_TPU_PALLAS_CNN). ISSUE 9 adds the fifth: the
whole RSSM dynamic step (pre-MLP + LN-GRU + prior/posterior head stacks)
as ONE kernel, `fused_rssm_step` below. Each kernel here

  - fuses what XLA would otherwise stage through HBM: the GRU kernel keeps the
    [B, 3H] pre-activation entirely in VMEM between the MXU matmul, the
    layernorm moments, and the gate math; the two-hot kernel never
    materializes the [N, K] two-hot target at all;
  - differentiates: forward runs the kernel, backward is an analytic VJP
    (two-hot, symlog) or a recompute-in-XLA VJP (GRU) so training numerics
    stay exact;
  - degrades gracefully: `use_pallas()` gates on the backend, the
    SHEEPRL_TPU_PALLAS env var forces on/off, and interpret mode runs the
    same kernels on CPU for numerics tests.

Callers (nn.recurrent.LayerNormGRUCell, ops.distributions.TwoHotEncoding-
Distribution) fall back to their plain-XLA paths whenever the kernels are
disabled or the shapes are unsupported, so behavior is identical either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = [
    "use_pallas",
    "set_pallas",
    "layernorm_gru_cell",
    "fused_rssm_step",
    "rssm_step_reference",
    "fused_int8_trunk",
    "int8_trunk_reference",
    "fused_int8_trunk_supported",
    "two_hot_log_prob",
    "symlog",
    "symexp",
]

_FORCED: bool | None = None
_INTERPRET = False  # tests flip this to run kernels on CPU


def set_pallas(enabled: bool | None, interpret: bool = False) -> None:
    """Force kernels on/off (None = auto: on when the default backend is
    TPU). `interpret=True` runs kernels in the Pallas interpreter (CPU)."""
    global _FORCED, _INTERPRET
    _FORCED, _INTERPRET = enabled, interpret


def _interpret_mode() -> bool:
    """Read the current interpret flag at trace time (pallas_cnn and other
    kernel modules must see flips made after their import)."""
    return _INTERPRET


@functools.cache
def _backend_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _env_flag(name: str) -> bool | None:
    env = os.environ.get(name, "").lower()
    if env in ("1", "on", "true"):
        return True
    if env in ("0", "off", "false"):
        return False
    return None


def use_pallas(kind: str | None = None) -> bool:
    """Master gate, optionally refined per kernel family via
    SHEEPRL_TPU_PALLAS_<KIND> (KIND in GRU|RSSM|TWO_HOT|SYMLOG|CNN|
    SAC_TRUNK) — the bench uses the per-kernel switches to attribute
    wins/losses and keep only winners."""
    if _FORCED is not None:
        enabled = _FORCED
    else:
        master = _env_flag("SHEEPRL_TPU_PALLAS")
        enabled = _backend_is_tpu() if master is None else master
    if enabled and kind is not None:
        per_kind = _env_flag(f"SHEEPRL_TPU_PALLAS_{kind.upper()}")
        if per_kind is not None:
            return per_kind
    return enabled


def _block_all(shape_dtypes):
    return [pl.BlockSpec(memory_space=_VMEM) for _ in shape_dtypes]


# =============================================================================
# LayerNorm-GRU cell
# =============================================================================


def _gru_kernel(x_ref, h_ref, w_ref, scale_ref, offset_ref, out_ref, *, eps):
    """One fused step: [x,h] @ W -> layernorm -> reset/cand/update gates.

    Everything after the MXU matmul is VPU work on a [B, 3H] block that never
    leaves VMEM — the fusion XLA can't be relied on to produce inside a scan
    body (it re-materializes the pre-activation in HBM between the matmul and
    the normalization reductions)."""
    xh = jnp.concatenate([x_ref[:], h_ref[:]], axis=-1)
    parts = jnp.dot(xh, w_ref[:], preferred_element_type=jnp.float32)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    centered = parts - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    parts = centered * jax.lax.rsqrt(var + eps) * scale_ref[:] + offset_ref[:]
    hidden = h_ref.shape[-1]
    r = parts[:, :hidden]
    c = parts[:, hidden : 2 * hidden]
    u = parts[:, 2 * hidden :]
    update = jax.nn.sigmoid(u - 1.0)  # Hafner update-bias trick
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    out = update * cand + (1.0 - update) * h_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)


def _gru_kernel_with_residuals(
    x_ref, h_ref, w_ref, scale_ref, offset_ref, out_ref, hat_ref, rstd_ref, *, eps
):
    """Forward used under differentiation: additionally writes the normalized
    pre-gate activations and the per-row inverse stddev, from which the
    backward reconstructs everything with elementwise math + two matmuls
    (no full recompute)."""
    xh = jnp.concatenate([x_ref[:], h_ref[:]], axis=-1)
    parts = jnp.dot(xh, w_ref[:], preferred_element_type=jnp.float32)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    centered = parts - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    hat = centered * rstd
    post = hat * scale_ref[:] + offset_ref[:]
    hidden = h_ref.shape[-1]
    r = post[:, :hidden]
    c = post[:, hidden : 2 * hidden]
    u = post[:, 2 * hidden :]
    update = jax.nn.sigmoid(u - 1.0)
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    out = update * cand + (1.0 - update) * h_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)
    hat_ref[:] = hat
    rstd_ref[:] = rstd


def _gru_forward_with_residuals(x, h, w, scale, offset, eps):
    batch, hidden = h.shape
    dx = x.shape[-1]
    bn = min(_GRU_BLOCK_ROWS, batch)
    return pl.pallas_call(
        functools.partial(_gru_kernel_with_residuals, eps=eps),
        grid=(_cdiv(batch, bn),),
        out_shape=(
            jax.ShapeDtypeStruct((batch, hidden), x.dtype),
            jax.ShapeDtypeStruct((batch, 3 * hidden), jnp.float32),
            jax.ShapeDtypeStruct((batch, 1), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((bn, dx), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, 3 * hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
        ),
        interpret=_INTERPRET,
    )(x, h, w, scale, offset)


def _gru_reference(x, h, w, scale, offset, eps):
    """Plain-XLA twin of the kernel (used for the recompute backward and as
    the numerics oracle in tests)."""
    parts = jnp.concatenate([x, h], axis=-1) @ w
    parts32 = parts.astype(jnp.float32)
    mean = jnp.mean(parts32, axis=-1, keepdims=True)
    var = jnp.var(parts32, axis=-1, keepdims=True)
    parts = ((parts32 - mean) * jax.lax.rsqrt(var + eps) * scale + offset).astype(
        x.dtype
    )
    r, c, u = jnp.split(parts, 3, axis=-1)
    update = jax.nn.sigmoid(u - 1.0)
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    return update * cand + (1.0 - update) * h


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


_GRU_BLOCK_ROWS = 256  # VMEM budget: [256, 3H] blocks + the full weight


def _gru_forward(x, h, w, scale, offset, eps):
    batch, hidden = h.shape
    dx = x.shape[-1]
    bn = min(_GRU_BLOCK_ROWS, batch)
    return pl.pallas_call(
        functools.partial(_gru_kernel, eps=eps),
        grid=(_cdiv(batch, bn),),
        out_shape=jax.ShapeDtypeStruct((batch, hidden), x.dtype),
        in_specs=[
            pl.BlockSpec((bn, dx), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x, h, w, scale, offset)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def layernorm_gru_cell(x, h, w, scale, offset, eps=1e-5):
    """Fused LayerNorm-GRU step: x [B, Dx], h [B, H], w [Dx+H, 3H],
    scale/offset [3H] -> new h [B, H]. Forward is the Pallas kernel; backward
    recomputes through the XLA twin (exact, and the [B, 3H] residual never
    needs saving)."""
    return _gru_forward(x, h, w, scale, offset, eps)


def _gru_fwd(x, h, w, scale, offset, eps):
    out, hat, rstd = _gru_forward_with_residuals(x, h, w, scale, offset, eps)
    return out, (x, h, w, scale, offset, hat, rstd)


def _gru_bwd(eps, residuals, g):
    """Analytic backward from the saved normalized activations: elementwise
    gate/LN chain rules plus the two unavoidable matmuls (dW, dxh)."""
    x, h, w, scale, offset, hat, rstd = residuals
    hidden = h.shape[-1]
    g = g.astype(jnp.float32)

    post = hat * scale + offset
    r = post[:, :hidden]
    c = post[:, hidden : 2 * hidden]
    u = post[:, 2 * hidden :]
    sr = jax.nn.sigmoid(r)
    pre_tanh = sr * c
    cand = jnp.tanh(pre_tanh)
    update = jax.nn.sigmoid(u - 1.0)

    d_update = g * (cand - h)
    d_cand = g * update
    dh_direct = g * (1.0 - update)
    d_u = d_update * update * (1.0 - update)
    d_pre = d_cand * (1.0 - cand * cand)
    d_c = d_pre * sr
    d_r = d_pre * c * sr * (1.0 - sr)
    dpost = jnp.concatenate([d_r, d_c, d_u], axis=-1)

    dscale = jnp.sum(dpost * hat, axis=0)
    doffset = jnp.sum(dpost, axis=0)
    dhat = dpost * scale
    # layernorm backward given hat and rstd
    m1 = jnp.mean(dhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dhat * hat, axis=-1, keepdims=True)
    dparts = rstd * (dhat - m1 - hat * m2)

    xh = jnp.concatenate([x, h], axis=-1)
    dw = xh.astype(jnp.float32).T @ dparts
    dxh = dparts @ w.astype(jnp.float32).T
    dx = dxh[:, : x.shape[-1]].astype(x.dtype)
    dh = (dxh[:, x.shape[-1] :] + dh_direct).astype(h.dtype)
    return dx, dh, dw.astype(w.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


layernorm_gru_cell.defvjp(_gru_fwd, _gru_bwd)


# =============================================================================
# Fused RSSM dynamic step (ISSUE 9 tentpole b)
# =============================================================================
#
# The DreamerV3 dynamic step is six tiny matmuls with elementwise/LN glue:
#
#   z        = act(LN(x @ Wm))                      # RecurrentModel.mlp
#   h'       = LayerNormGRU(z, h; Wg, sg, og)       # the recurrence
#   prior    = (act(LN(h' @ Wt1)) @ Wt2) + bt2      # transition head
#   post     = (act(LN([h', emb] @ Wr1)) @ Wr2)+br2 # representation head
#
# At RSSM shapes ([B=16] rows through 512-wide layers, T=64 sequential scan
# steps) each stage is far below the MXU's efficient arithmetic intensity
# and XLA stages every intermediate through HBM inside the scan body — the
# per-step launch+memory overhead rivals the math (the round-4 duty-cycle
# analysis; same diagnosis as the RL-kernel fusion results of
# arXiv:2311.09445). This kernel runs the whole step out of VMEM: matmul
# operands stay in the input dtype (bf16 under the mixed-precision policy —
# the MXU's native reduced-precision path), every accumulation/normalization
# runs in f32 (`preferred_element_type`), and only three arrays leave the
# kernel: h' in the compute dtype and the two raw head outputs in f32 (the
# unimix/sampling fp32 island consumes them directly, so the bf16 audit
# sees no extra upcasts).
#
# The backward differentiates `rssm_step_reference` — a plain-XLA twin with
# IDENTICAL accumulation semantics — via jax.vjp (recompute-in-XLA, the
# same policy as the GRU kernel's documented backward): gradients are exact
# w.r.t. the twin, and the [B, ·] residuals never need saving.

_FUSED_VMEM_BUDGET_BYTES = 10 * 1024 * 1024  # weights must co-reside in VMEM

_KERNEL_ACTS = {
    "silu": jax.nn.silu,
    "relu": jax.nn.relu,
    "tanh": jnp.tanh,
    "elu": jax.nn.elu,
    "gelu": jax.nn.gelu,
    "identity": lambda x: x,
}


def _ln(x32, scale, offset, eps):
    """f32 layernorm over the trailing axis (in-kernel and in the twin)."""
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    centered = x32 - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    return centered * jax.lax.rsqrt(var + eps) * scale + offset


def _rssm_step_math(
    x, h, emb, wm, sm, om, wg, sg, og,
    wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    act, eps,
):
    """The shared step math: matmul operands in the input dtype, f32
    accumulations/normalizations/gates. Used verbatim by the Pallas kernel
    body and the XLA reference twin so the two are the same function."""
    act_fn = _KERNEL_ACTS[act]
    mlp_eps, gru_eps, head_eps = eps
    dt = x.dtype

    # RecurrentModel.mlp: Linear -> LN -> act
    z = jnp.dot(x, wm, preferred_element_type=jnp.float32)
    z = act_fn(_ln(z, sm, om, mlp_eps)).astype(dt)

    # LayerNorm-GRU (the _gru_kernel math)
    xh = jnp.concatenate([z, h], axis=-1)
    parts = _ln(
        jnp.dot(xh, wg, preferred_element_type=jnp.float32), sg, og, gru_eps
    )
    hidden = h.shape[-1]
    r = parts[:, :hidden]
    c = parts[:, hidden : 2 * hidden]
    u = parts[:, 2 * hidden :]
    update = jax.nn.sigmoid(u - 1.0)  # Hafner update-bias trick
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    h_new32 = update * cand + (1.0 - update) * h.astype(jnp.float32)
    h_new = h_new32.astype(dt)

    # transition head (prior): MLP hidden -> LN -> act -> logits Linear
    t1 = jnp.dot(h_new, wt1, preferred_element_type=jnp.float32)
    t1 = act_fn(_ln(t1, st1, ot1, head_eps)).astype(dt)
    prior_raw = jnp.dot(t1, wt2, preferred_element_type=jnp.float32) + bt2

    # representation head (posterior): same shape over [h', emb]
    he = jnp.concatenate([h_new, emb], axis=-1)
    r1 = jnp.dot(he, wr1, preferred_element_type=jnp.float32)
    r1 = act_fn(_ln(r1, sr1, or1, head_eps)).astype(dt)
    post_raw = jnp.dot(r1, wr2, preferred_element_type=jnp.float32) + br2

    return h_new, prior_raw, post_raw


def _fused_rssm_kernel(
    x_ref, h_ref, emb_ref, wm_ref, sm_ref, om_ref, wg_ref, sg_ref, og_ref,
    wt1_ref, st1_ref, ot1_ref, wt2_ref, bt2_ref,
    wr1_ref, sr1_ref, or1_ref, wr2_ref, br2_ref,
    h_out_ref, prior_ref, post_ref, *, act, eps,
):
    h_new, prior_raw, post_raw = _rssm_step_math(
        x_ref[:], h_ref[:], emb_ref[:],
        wm_ref[:], sm_ref[:], om_ref[:],
        wg_ref[:], sg_ref[:], og_ref[:],
        wt1_ref[:], st1_ref[:], ot1_ref[:], wt2_ref[:], bt2_ref[:],
        wr1_ref[:], sr1_ref[:], or1_ref[:], wr2_ref[:], br2_ref[:],
        act, eps,
    )
    h_out_ref[:] = h_new.astype(h_out_ref.dtype)
    prior_ref[:] = prior_raw
    post_ref[:] = post_raw


def rssm_step_reference(
    x, h, emb, wm, sm, om, wg, sg, og,
    wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    act="silu", eps=(1e-3, 1e-5, 1e-3),
):
    """Plain-XLA twin of the fused kernel: the numerics oracle for the
    parity tests and the function the custom VJP differentiates."""
    return _rssm_step_math(
        x, h, emb, wm, sm, om, wg, sg, og,
        wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
        act, tuple(eps),
    )


_RSSM_BLOCK_ROWS = 128  # [128 rows x (3R + heads)] f32 working set in VMEM


def _fused_rssm_forward(
    x, h, emb, wm, sm, om, wg, sg, og,
    wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    act, eps,
):
    batch, hidden = h.shape
    sd = wt2.shape[-1]
    bn = min(_RSSM_BLOCK_ROWS, batch)

    def rows(a):
        return pl.BlockSpec((bn, a.shape[-1]), lambda i: (i, 0), memory_space=_VMEM)

    def whole(a):
        if a.ndim == 1:
            return pl.BlockSpec(a.shape, lambda i: (0,), memory_space=_VMEM)
        return pl.BlockSpec(a.shape, lambda i: (0, 0), memory_space=_VMEM)

    return pl.pallas_call(
        functools.partial(_fused_rssm_kernel, act=act, eps=eps),
        grid=(_cdiv(batch, bn),),
        out_shape=(
            jax.ShapeDtypeStruct((batch, hidden), x.dtype),
            jax.ShapeDtypeStruct((batch, sd), jnp.float32),
            jax.ShapeDtypeStruct((batch, sd), jnp.float32),
        ),
        in_specs=[
            rows(x), rows(h), rows(emb),
            whole(wm), whole(sm), whole(om),
            whole(wg), whole(sg), whole(og),
            whole(wt1), whole(st1), whole(ot1), whole(wt2), whole(bt2),
            whole(wr1), whole(sr1), whole(or1), whole(wr2), whole(br2),
        ],
        out_specs=(
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, sd), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, sd), lambda i: (i, 0), memory_space=_VMEM),
        ),
        interpret=_INTERPRET,
    )(
        x, h, emb, wm, sm, om, wg, sg, og,
        wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(19, 20))
def fused_rssm_step(
    x, h, emb, wm, sm, om, wg, sg, og,
    wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    act="silu", eps=(1e-3, 1e-5, 1e-3),
):
    """One fused RSSM dynamic step.

    x [B, Dx] (posterior_flat ++ action), h [B, R], emb [B, E]; weights in
    the compute dtype (callers cast their f32 masters, like the Linear
    layers do), LN scales/offsets and head biases in f32.
    Returns (h' [B, R] compute dtype, prior_raw [B, S*D] f32,
    post_raw [B, S*D] f32) — raw pre-unimix logits; sampling stays outside
    (it needs PRNG keys and the f32 island).
    `eps` is (mlp_eps, gru_eps, head_eps); `act` must be a _KERNEL_ACTS key.
    """
    return _fused_rssm_forward(
        x, h, emb, wm, sm, om, wg, sg, og,
        wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
        act, tuple(eps),
    )


def _fused_rssm_fwd(
    x, h, emb, wm, sm, om, wg, sg, og,
    wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    act, eps,
):
    out = _fused_rssm_forward(
        x, h, emb, wm, sm, om, wg, sg, og,
        wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
        act, tuple(eps),
    )
    residuals = (
        x, h, emb, wm, sm, om, wg, sg, og,
        wt1, st1, ot1, wt2, bt2, wr1, sr1, or1, wr2, br2,
    )
    return out, residuals


def _fused_rssm_bwd(act, eps, residuals, g):
    """Recompute-in-XLA backward: one extra forward through the twin, exact
    gradients w.r.t. the kernel's accumulation semantics."""
    _, vjp = jax.vjp(
        lambda *args: _rssm_step_math(*args, act, tuple(eps)), *residuals
    )
    return vjp(g)


fused_rssm_step.defvjp(_fused_rssm_fwd, _fused_rssm_bwd)


def fused_rssm_supported(act: str, *weights) -> bool:
    """Trace-time dispatch guard shared with the RSSM module: the activation
    must have an in-kernel implementation and the step's weights must
    co-reside in VMEM with room for the row blocks."""
    if act not in _KERNEL_ACTS:
        return False
    total = sum(int(w.size) * w.dtype.itemsize for w in weights)
    return total <= _FUSED_VMEM_BUDGET_BYTES


# =============================================================================
# Fused int8 SAC trunk (ISSUE 20 tentpole c)
# =============================================================================
#
# The quantized SAC serve trunk is three int8 matmuls with relu glue:
#
#   a0   = relu((q(x  / s0) @ W0q) * ws0 + b0)     # trunk layer 0
#   a1   = relu((q(a0 / s1) @ W1q) * ws1 + b1)     # trunk layer 1
#   mean =      (q(a1 / sm) @ Wmq) * wsm + bm      # fc_mean head
#
# (q = round-to-nearest symmetric int8, ops/quant.py). At serve rung shapes
# ([B<=8] rows through 256-wide layers) every stage is far below the MXU's
# efficient arithmetic intensity and XLA stages each dequantized f32
# activation through HBM between layers — the same per-step overhead
# diagnosis as the fused RSSM step above. This kernel keeps the whole trunk
# in VMEM: int8 x int8 matmuls accumulate in int32 on the MXU's native
# int8 path, dequant/requant between layers is VPU work on blocks that
# never leave VMEM, and only the f32 `mean` leaves the kernel (the
# tanh * action_scale + action_bias squash stays outside in the f32
# island, exactly like sampling stays outside the RSSM kernel).
#
# Inference-only: no custom VJP — the serve tier never differentiates the
# policy, and the quality receipt in compile/decisions.py is measured
# against `int8_trunk_reference`, the plain-XLA twin sharing this math
# function verbatim (integer matmuls + same-order f32 ops, so kernel vs
# twin parity is exact, not approximate).


def _int8_trunk_math(
    x, s0, w0, ws0, b0, s1, w1, ws1, b1, sm, wm, wsm, bm
):
    """The shared trunk math, used verbatim by the Pallas kernel body and
    the XLA reference twin. Layer boundaries are f32 islands; matmuls are
    int8 x int8 with int32 accumulation (`ops.quant.int8_linear`)."""
    from .quant import int8_linear

    a0 = jax.nn.relu(int8_linear(x, s0, w0, ws0, b0))
    a1 = jax.nn.relu(int8_linear(a0, s1, w1, ws1, b1))
    return int8_linear(a1, sm, wm, wsm, bm)


def _fused_int8_kernel(
    x_ref, s0_ref, w0_ref, ws0_ref, b0_ref,
    s1_ref, w1_ref, ws1_ref, b1_ref,
    sm_ref, wm_ref, wsm_ref, bm_ref, out_ref,
):
    out_ref[:] = _int8_trunk_math(
        x_ref[:], s0_ref[:], w0_ref[:], ws0_ref[:], b0_ref[:],
        s1_ref[:], w1_ref[:], ws1_ref[:], b1_ref[:],
        sm_ref[:], wm_ref[:], wsm_ref[:], bm_ref[:],
    )


def int8_trunk_reference(x, s0, w0, ws0, b0, s1, w1, ws1, b1, sm, wm, wsm, bm):
    """Plain-XLA twin of the fused kernel: the numerics oracle for the
    parity tests and the fallback when the kernel is gated off."""
    return _int8_trunk_math(
        x, s0, w0, ws0, b0, s1, w1, ws1, b1, sm, wm, wsm, bm
    )


_INT8_BLOCK_ROWS = 128  # int8 min tile is (32, 128); row blocks stay modest


def fused_int8_trunk(x, s0, w0, ws0, b0, s1, w1, ws1, b1, sm, wm, wsm, bm):
    """One fused quantized SAC trunk step: x [B, Dx] f32, per layer
    (in_scale [Din] f32, w_q [Din, Dout] int8, w_scale [Dout] f32,
    bias [Dout] f32) -> raw mean [B, A] f32 (pre-squash)."""
    batch = x.shape[0]
    out_dim = wm.shape[-1]
    bn = min(_INT8_BLOCK_ROWS, batch)

    def rows(a):
        return pl.BlockSpec((bn, a.shape[-1]), lambda i: (i, 0), memory_space=_VMEM)

    def whole(a):
        if a.ndim == 1:
            return pl.BlockSpec(a.shape, lambda i: (0,), memory_space=_VMEM)
        return pl.BlockSpec(a.shape, lambda i: (0, 0), memory_space=_VMEM)

    return pl.pallas_call(
        _fused_int8_kernel,
        grid=(_cdiv(batch, bn),),
        out_shape=jax.ShapeDtypeStruct((batch, out_dim), jnp.float32),
        in_specs=[
            rows(x),
            whole(s0), whole(w0), whole(ws0), whole(b0),
            whole(s1), whole(w1), whole(ws1), whole(b1),
            whole(sm), whole(wm), whole(wsm), whole(bm),
        ],
        out_specs=pl.BlockSpec(
            (bn, out_dim), lambda i: (i, 0), memory_space=_VMEM
        ),
        interpret=_INTERPRET,
    )(x, s0, w0, ws0, b0, s1, w1, ws1, b1, sm, wm, wsm, bm)


def fused_int8_trunk_supported(*weights) -> bool:
    """Trace-time dispatch guard (the fused_rssm_supported pattern): the
    trunk's quantized weights + scales + biases must co-reside in VMEM
    with room for the row blocks."""
    total = sum(int(w.size) * w.dtype.itemsize for w in weights)
    return total <= _FUSED_VMEM_BUDGET_BYTES


# =============================================================================
# Two-hot cross-entropy (the DreamerV3 reward/critic log-prob)
# =============================================================================


def _two_hot_log_prob_kernel(x_ref, logits_ref, bins_ref, out_ref):
    """log p(x) under a categorical over `bins` with two-hot targets, without
    materializing the [N, K] target: for each row, find the bracketing bins
    by comparison counts, turn distances into the two interpolation weights,
    and contract against the log-softmax row on the fly."""
    x = x_ref[:]  # [N, 1]
    logits = logits_ref[:]  # [N, K]
    bins = bins_ref[:]  # [1, K]
    k = logits.shape[-1]

    log_z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1, keepdims=True)
    log_probs = logits.astype(jnp.float32) - log_z  # [N, K]

    below = jnp.sum((bins <= x).astype(jnp.int32), axis=-1, keepdims=True) - 1
    above = k - jnp.sum((bins > x).astype(jnp.int32), axis=-1, keepdims=True)
    below = jnp.clip(below, 0, k - 1)
    above = jnp.clip(above, 0, k - 1)
    equal = below == above

    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)  # [N, K]
    below_onehot = (idx == below).astype(jnp.float32)
    above_onehot = (idx == above).astype(jnp.float32)
    bin_below = jnp.sum(bins * below_onehot, axis=-1, keepdims=True)
    bin_above = jnp.sum(bins * above_onehot, axis=-1, keepdims=True)
    d_below = jnp.where(equal, 1.0, jnp.abs(bin_below - x))
    d_above = jnp.where(equal, 1.0, jnp.abs(bin_above - x))
    total = d_below + d_above
    w_below = d_above / total
    w_above = d_below / total

    lp_below = jnp.sum(log_probs * below_onehot, axis=-1, keepdims=True)
    lp_above = jnp.sum(log_probs * above_onehot, axis=-1, keepdims=True)
    out_ref[:] = w_below * lp_below + w_above * lp_above


_TWO_HOT_BLOCK_ROWS = 1024  # [1024, K~255] f32 working set stays well under VMEM


def _two_hot_forward(x, logits, bins):
    n, k = logits.shape
    bn = min(_TWO_HOT_BLOCK_ROWS, n)
    return pl.pallas_call(
        _two_hot_log_prob_kernel,
        grid=(_cdiv(n, bn),),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        in_specs=[
            pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, k), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x, logits, bins)


@jax.custom_vjp
def two_hot_log_prob(x, logits, bins):
    """x [N, 1] scalar targets, logits [N, K], bins [1, K] -> log-prob [N, 1].

    Gradient flows to `logits` only (the DreamerV3 losses treat the two-hot
    target as a constant): d/dlogits = (target - softmax(logits)) * g."""
    return _two_hot_forward(x, logits, bins)


def _two_hot_fwd(x, logits, bins):
    return _two_hot_forward(x, logits, bins), (x, logits, bins)


def _two_hot_bwd(residuals, g):
    from .math import two_hot as dense_two_hot

    x, logits, bins = residuals
    target = dense_two_hot(x[:, 0], bins[0])  # [N, K]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    dlogits = ((target - probs) * g).astype(logits.dtype)
    return jnp.zeros_like(x), dlogits, jnp.zeros_like(bins)


two_hot_log_prob.defvjp(_two_hot_fwd, _two_hot_bwd)


# =============================================================================
# symlog / symexp
# =============================================================================


def _symlog_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _elementwise(kernel, x):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=_VMEM)],
        out_specs=pl.BlockSpec(memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x)


@jax.custom_vjp
def symlog(x):
    """sign(x) * log1p(|x|) with the analytic gradient 1 / (1 + |x|)."""
    return _elementwise(_symlog_kernel, x)


def _symlog_fwd(x):
    return _elementwise(_symlog_kernel, x), x


def _symlog_bwd(x, g):
    return (g / (1.0 + jnp.abs(x)),)


symlog.defvjp(_symlog_fwd, _symlog_bwd)


@jax.custom_vjp
def symexp(x):
    """sign(x) * (exp(|x|) - 1) with the analytic gradient exp(|x|)."""
    return _elementwise(_symexp_kernel, x)


def _symexp_fwd(x):
    return _elementwise(_symexp_kernel, x), x


def _symexp_bwd(x, g):
    return (g * jnp.exp(jnp.abs(x)),)


symexp.defvjp(_symexp_fwd, _symexp_bwd)
