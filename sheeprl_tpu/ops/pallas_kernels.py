"""Pallas TPU kernels for the framework's hot ops.

The BASELINE.md north star names four kernel targets: the LayerNorm-GRU cell
(the RSSM scan body, reference /root/reference/sheeprl/models/models.py:330-402),
symlog/symexp (reference utils/utils.py:125-133), the two-hot log-prob
(reference utils/distribution.py:220-266), and the CNN encoder/decoder
stages (ops/pallas_cnn.py — fused conv/deconv + LayerNorm + SiLU,
per-family switch SHEEPRL_TPU_PALLAS_CNN). Each kernel here

  - fuses what XLA would otherwise stage through HBM: the GRU kernel keeps the
    [B, 3H] pre-activation entirely in VMEM between the MXU matmul, the
    layernorm moments, and the gate math; the two-hot kernel never
    materializes the [N, K] two-hot target at all;
  - differentiates: forward runs the kernel, backward is an analytic VJP
    (two-hot, symlog) or a recompute-in-XLA VJP (GRU) so training numerics
    stay exact;
  - degrades gracefully: `use_pallas()` gates on the backend, the
    SHEEPRL_TPU_PALLAS env var forces on/off, and interpret mode runs the
    same kernels on CPU for numerics tests.

Callers (nn.recurrent.LayerNormGRUCell, ops.distributions.TwoHotEncoding-
Distribution) fall back to their plain-XLA paths whenever the kernels are
disabled or the shapes are unsupported, so behavior is identical either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

__all__ = [
    "use_pallas",
    "set_pallas",
    "layernorm_gru_cell",
    "two_hot_log_prob",
    "symlog",
    "symexp",
]

_FORCED: bool | None = None
_INTERPRET = False  # tests flip this to run kernels on CPU


def set_pallas(enabled: bool | None, interpret: bool = False) -> None:
    """Force kernels on/off (None = auto: on when the default backend is
    TPU). `interpret=True` runs kernels in the Pallas interpreter (CPU)."""
    global _FORCED, _INTERPRET
    _FORCED, _INTERPRET = enabled, interpret


def _interpret_mode() -> bool:
    """Read the current interpret flag at trace time (pallas_cnn and other
    kernel modules must see flips made after their import)."""
    return _INTERPRET


@functools.cache
def _backend_is_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _env_flag(name: str) -> bool | None:
    env = os.environ.get(name, "").lower()
    if env in ("1", "on", "true"):
        return True
    if env in ("0", "off", "false"):
        return False
    return None


def use_pallas(kind: str | None = None) -> bool:
    """Master gate, optionally refined per kernel family via
    SHEEPRL_TPU_PALLAS_<KIND> (KIND in GRU|TWO_HOT|SYMLOG|CNN) — the bench
    uses the per-kernel switches to attribute wins/losses and keep only
    winners."""
    if _FORCED is not None:
        enabled = _FORCED
    else:
        master = _env_flag("SHEEPRL_TPU_PALLAS")
        enabled = _backend_is_tpu() if master is None else master
    if enabled and kind is not None:
        per_kind = _env_flag(f"SHEEPRL_TPU_PALLAS_{kind.upper()}")
        if per_kind is not None:
            return per_kind
    return enabled


def _block_all(shape_dtypes):
    return [pl.BlockSpec(memory_space=_VMEM) for _ in shape_dtypes]


# =============================================================================
# LayerNorm-GRU cell
# =============================================================================


def _gru_kernel(x_ref, h_ref, w_ref, scale_ref, offset_ref, out_ref, *, eps):
    """One fused step: [x,h] @ W -> layernorm -> reset/cand/update gates.

    Everything after the MXU matmul is VPU work on a [B, 3H] block that never
    leaves VMEM — the fusion XLA can't be relied on to produce inside a scan
    body (it re-materializes the pre-activation in HBM between the matmul and
    the normalization reductions)."""
    xh = jnp.concatenate([x_ref[:], h_ref[:]], axis=-1)
    parts = jnp.dot(xh, w_ref[:], preferred_element_type=jnp.float32)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    centered = parts - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    parts = centered * jax.lax.rsqrt(var + eps) * scale_ref[:] + offset_ref[:]
    hidden = h_ref.shape[-1]
    r = parts[:, :hidden]
    c = parts[:, hidden : 2 * hidden]
    u = parts[:, 2 * hidden :]
    update = jax.nn.sigmoid(u - 1.0)  # Hafner update-bias trick
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    out = update * cand + (1.0 - update) * h_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)


def _gru_kernel_with_residuals(
    x_ref, h_ref, w_ref, scale_ref, offset_ref, out_ref, hat_ref, rstd_ref, *, eps
):
    """Forward used under differentiation: additionally writes the normalized
    pre-gate activations and the per-row inverse stddev, from which the
    backward reconstructs everything with elementwise math + two matmuls
    (no full recompute)."""
    xh = jnp.concatenate([x_ref[:], h_ref[:]], axis=-1)
    parts = jnp.dot(xh, w_ref[:], preferred_element_type=jnp.float32)
    mean = jnp.mean(parts, axis=-1, keepdims=True)
    centered = parts - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    hat = centered * rstd
    post = hat * scale_ref[:] + offset_ref[:]
    hidden = h_ref.shape[-1]
    r = post[:, :hidden]
    c = post[:, hidden : 2 * hidden]
    u = post[:, 2 * hidden :]
    update = jax.nn.sigmoid(u - 1.0)
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    out = update * cand + (1.0 - update) * h_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)
    hat_ref[:] = hat
    rstd_ref[:] = rstd


def _gru_forward_with_residuals(x, h, w, scale, offset, eps):
    batch, hidden = h.shape
    dx = x.shape[-1]
    bn = min(_GRU_BLOCK_ROWS, batch)
    return pl.pallas_call(
        functools.partial(_gru_kernel_with_residuals, eps=eps),
        grid=(_cdiv(batch, bn),),
        out_shape=(
            jax.ShapeDtypeStruct((batch, hidden), x.dtype),
            jax.ShapeDtypeStruct((batch, 3 * hidden), jnp.float32),
            jax.ShapeDtypeStruct((batch, 1), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((bn, dx), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=(
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, 3 * hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
        ),
        interpret=_INTERPRET,
    )(x, h, w, scale, offset)


def _gru_reference(x, h, w, scale, offset, eps):
    """Plain-XLA twin of the kernel (used for the recompute backward and as
    the numerics oracle in tests)."""
    parts = jnp.concatenate([x, h], axis=-1) @ w
    parts32 = parts.astype(jnp.float32)
    mean = jnp.mean(parts32, axis=-1, keepdims=True)
    var = jnp.var(parts32, axis=-1, keepdims=True)
    parts = ((parts32 - mean) * jax.lax.rsqrt(var + eps) * scale + offset).astype(
        x.dtype
    )
    r, c, u = jnp.split(parts, 3, axis=-1)
    update = jax.nn.sigmoid(u - 1.0)
    cand = jnp.tanh(jax.nn.sigmoid(r) * c)
    return update * cand + (1.0 - update) * h


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


_GRU_BLOCK_ROWS = 256  # VMEM budget: [256, 3H] blocks + the full weight


def _gru_forward(x, h, w, scale, offset, eps):
    batch, hidden = h.shape
    dx = x.shape[-1]
    bn = min(_GRU_BLOCK_ROWS, batch)
    return pl.pallas_call(
        functools.partial(_gru_kernel, eps=eps),
        grid=(_cdiv(batch, bn),),
        out_shape=jax.ShapeDtypeStruct((batch, hidden), x.dtype),
        in_specs=[
            pl.BlockSpec((bn, dx), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec(w.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bn, hidden), lambda i: (i, 0), memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x, h, w, scale, offset)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def layernorm_gru_cell(x, h, w, scale, offset, eps=1e-5):
    """Fused LayerNorm-GRU step: x [B, Dx], h [B, H], w [Dx+H, 3H],
    scale/offset [3H] -> new h [B, H]. Forward is the Pallas kernel; backward
    recomputes through the XLA twin (exact, and the [B, 3H] residual never
    needs saving)."""
    return _gru_forward(x, h, w, scale, offset, eps)


def _gru_fwd(x, h, w, scale, offset, eps):
    out, hat, rstd = _gru_forward_with_residuals(x, h, w, scale, offset, eps)
    return out, (x, h, w, scale, offset, hat, rstd)


def _gru_bwd(eps, residuals, g):
    """Analytic backward from the saved normalized activations: elementwise
    gate/LN chain rules plus the two unavoidable matmuls (dW, dxh)."""
    x, h, w, scale, offset, hat, rstd = residuals
    hidden = h.shape[-1]
    g = g.astype(jnp.float32)

    post = hat * scale + offset
    r = post[:, :hidden]
    c = post[:, hidden : 2 * hidden]
    u = post[:, 2 * hidden :]
    sr = jax.nn.sigmoid(r)
    pre_tanh = sr * c
    cand = jnp.tanh(pre_tanh)
    update = jax.nn.sigmoid(u - 1.0)

    d_update = g * (cand - h)
    d_cand = g * update
    dh_direct = g * (1.0 - update)
    d_u = d_update * update * (1.0 - update)
    d_pre = d_cand * (1.0 - cand * cand)
    d_c = d_pre * sr
    d_r = d_pre * c * sr * (1.0 - sr)
    dpost = jnp.concatenate([d_r, d_c, d_u], axis=-1)

    dscale = jnp.sum(dpost * hat, axis=0)
    doffset = jnp.sum(dpost, axis=0)
    dhat = dpost * scale
    # layernorm backward given hat and rstd
    m1 = jnp.mean(dhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dhat * hat, axis=-1, keepdims=True)
    dparts = rstd * (dhat - m1 - hat * m2)

    xh = jnp.concatenate([x, h], axis=-1)
    dw = xh.astype(jnp.float32).T @ dparts
    dxh = dparts @ w.astype(jnp.float32).T
    dx = dxh[:, : x.shape[-1]].astype(x.dtype)
    dh = (dxh[:, x.shape[-1] :] + dh_direct).astype(h.dtype)
    return dx, dh, dw.astype(w.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


layernorm_gru_cell.defvjp(_gru_fwd, _gru_bwd)


# =============================================================================
# Two-hot cross-entropy (the DreamerV3 reward/critic log-prob)
# =============================================================================


def _two_hot_log_prob_kernel(x_ref, logits_ref, bins_ref, out_ref):
    """log p(x) under a categorical over `bins` with two-hot targets, without
    materializing the [N, K] target: for each row, find the bracketing bins
    by comparison counts, turn distances into the two interpolation weights,
    and contract against the log-softmax row on the fly."""
    x = x_ref[:]  # [N, 1]
    logits = logits_ref[:]  # [N, K]
    bins = bins_ref[:]  # [1, K]
    k = logits.shape[-1]

    log_z = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1, keepdims=True)
    log_probs = logits.astype(jnp.float32) - log_z  # [N, K]

    below = jnp.sum((bins <= x).astype(jnp.int32), axis=-1, keepdims=True) - 1
    above = k - jnp.sum((bins > x).astype(jnp.int32), axis=-1, keepdims=True)
    below = jnp.clip(below, 0, k - 1)
    above = jnp.clip(above, 0, k - 1)
    equal = below == above

    idx = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)  # [N, K]
    below_onehot = (idx == below).astype(jnp.float32)
    above_onehot = (idx == above).astype(jnp.float32)
    bin_below = jnp.sum(bins * below_onehot, axis=-1, keepdims=True)
    bin_above = jnp.sum(bins * above_onehot, axis=-1, keepdims=True)
    d_below = jnp.where(equal, 1.0, jnp.abs(bin_below - x))
    d_above = jnp.where(equal, 1.0, jnp.abs(bin_above - x))
    total = d_below + d_above
    w_below = d_above / total
    w_above = d_below / total

    lp_below = jnp.sum(log_probs * below_onehot, axis=-1, keepdims=True)
    lp_above = jnp.sum(log_probs * above_onehot, axis=-1, keepdims=True)
    out_ref[:] = w_below * lp_below + w_above * lp_above


_TWO_HOT_BLOCK_ROWS = 1024  # [1024, K~255] f32 working set stays well under VMEM


def _two_hot_forward(x, logits, bins):
    n, k = logits.shape
    bn = min(_TWO_HOT_BLOCK_ROWS, n)
    return pl.pallas_call(
        _two_hot_log_prob_kernel,
        grid=(_cdiv(n, bn),),
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        in_specs=[
            pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((bn, k), lambda i: (i, 0), memory_space=_VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=_VMEM),
        ],
        out_specs=pl.BlockSpec((bn, 1), lambda i: (i, 0), memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x, logits, bins)


@jax.custom_vjp
def two_hot_log_prob(x, logits, bins):
    """x [N, 1] scalar targets, logits [N, K], bins [1, K] -> log-prob [N, 1].

    Gradient flows to `logits` only (the DreamerV3 losses treat the two-hot
    target as a constant): d/dlogits = (target - softmax(logits)) * g."""
    return _two_hot_forward(x, logits, bins)


def _two_hot_fwd(x, logits, bins):
    return _two_hot_forward(x, logits, bins), (x, logits, bins)


def _two_hot_bwd(residuals, g):
    from .math import two_hot as dense_two_hot

    x, logits, bins = residuals
    target = dense_two_hot(x[:, 0], bins[0])  # [N, K]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    dlogits = ((target - probs) * g).astype(logits.dtype)
    return jnp.zeros_like(x), dlogits, jnp.zeros_like(bins)


two_hot_log_prob.defvjp(_two_hot_fwd, _two_hot_bwd)


# =============================================================================
# symlog / symexp
# =============================================================================


def _symlog_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.sign(x) * jnp.log1p(jnp.abs(x))


def _symexp_kernel(x_ref, out_ref):
    x = x_ref[:]
    out_ref[:] = jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def _elementwise(kernel, x):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=_VMEM)],
        out_specs=pl.BlockSpec(memory_space=_VMEM),
        interpret=_INTERPRET,
    )(x)


@jax.custom_vjp
def symlog(x):
    """sign(x) * log1p(|x|) with the analytic gradient 1 / (1 + |x|)."""
    return _elementwise(_symlog_kernel, x)


def _symlog_fwd(x):
    return _elementwise(_symlog_kernel, x), x


def _symlog_bwd(x, g):
    return (g / (1.0 + jnp.abs(x)),)


symlog.defvjp(_symlog_fwd, _symlog_bwd)


@jax.custom_vjp
def symexp(x):
    """sign(x) * (exp(|x|) - 1) with the analytic gradient exp(|x|)."""
    return _elementwise(_symexp_kernel, x)


def _symexp_fwd(x):
    return _elementwise(_symexp_kernel, x), x


def _symexp_bwd(x, g):
    return (g * jnp.exp(jnp.abs(x)),)


symexp.defvjp(_symexp_fwd, _symexp_bwd)
