"""Core RL math as pure, jittable functions (`lax.scan` for all recurrences).

Semantics mirror the reference (/root/reference/sheeprl/utils/utils.py:8-133,
algos/dreamer_v3/utils.py:45-56) but every reverse-time recursion is a single
`lax.scan` — traced once, fused by XLA — instead of a Python loop over T.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "symlog",
    "symexp",
    "gae",
    "lambda_values",
    "lambda_values_dv2",
    "lambda_values_dv3",
    "two_hot",
    "normalize",
    "polynomial_decay",
]


def symlog(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jax.Array) -> jax.Array:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def gae(
    rewards: jax.Array,
    values: jax.Array,
    dones: jax.Array,
    next_value: jax.Array,
    next_done: jax.Array,
    gamma: float,
    gae_lambda: float,
) -> tuple[jax.Array, jax.Array]:
    """Generalized advantage estimation (arXiv:1506.02438).

    All of rewards/values/dones are time-major `[T, ...]`; `next_value` /
    `next_done` bootstrap the step after the rollout. Returns
    (returns, advantages), both `[T, ...]`. Matches the reference recursion
    (/root/reference/sheeprl/utils/utils.py:8-48).
    """
    dones = dones.astype(jnp.float32)
    next_nonterminal = jnp.concatenate(
        [1.0 - dones[1:], (1.0 - next_done.astype(jnp.float32))[None]], axis=0
    )
    next_values = jnp.concatenate([values[1:], next_value[None]], axis=0)
    deltas = rewards + gamma * next_values * next_nonterminal - values

    def step(carry, inp):
        delta, nonterm = inp
        adv = delta + gamma * gae_lambda * nonterm * carry
        return adv, adv

    _, advantages = jax.lax.scan(
        step, jnp.zeros_like(next_value), (deltas, next_nonterminal), reverse=True
    )
    returns = advantages + values
    return returns, advantages


def lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    done_mask: jax.Array,
    last_values: jax.Array,
    horizon: int,
    lmbda: float = 0.95,
) -> jax.Array:
    """TD(lambda) targets for DreamerV1/V2 imagination
    (/root/reference/sheeprl/utils/utils.py:51-86). Output is `[horizon-1, ...]`;
    gradients flow through values/rewards. `done_mask` is the (already
    gamma-scaled) continuation mask the callers pass."""
    next_vals = jnp.concatenate(
        [values[1 : horizon - 1] * (1.0 - lmbda), last_values[None]], axis=0
    )
    deltas = rewards[: horizon - 1] + next_vals * done_mask[: horizon - 1]

    def step(carry, inp):
        delta, mask = inp
        lv = delta + lmbda * mask * carry
        return lv, lv

    _, out = jax.lax.scan(
        step,
        jnp.zeros_like(last_values),
        (deltas, done_mask[: horizon - 1]),
        reverse=True,
    )
    return out


def lambda_values_dv2(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: jax.Array | None = None,
    lmbda: float = 0.95,
) -> jax.Array:
    """DreamerV2 lambda-return variant with explicit bootstrap
    (/root/reference/sheeprl/algos/dreamer_v2/utils.py:63-80): inputs are
    `[H, ...]`, `bootstrap` is `[1, ...]` (zeros when None); `continues`
    already folds in gamma."""
    if bootstrap is None:
        bootstrap = jnp.zeros_like(values[-1:])
    next_vals = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_vals * (1.0 - lmbda)

    def step(carry, inp):
        i_t, c_t = inp
        agg = i_t + c_t * lmbda * carry
        return agg, agg

    _, out = jax.lax.scan(step, bootstrap[0], (inputs, continues), reverse=True)
    return out


def lambda_values_dv3(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    lmbda: float = 0.95,
) -> jax.Array:
    """DreamerV3 lambda-return variant
    (/root/reference/sheeprl/algos/dreamer_v3/utils.py:45-56): inputs are the
    1-step-shifted imagination tensors `[T, ...]`; recursion bootstraps from
    values[-1]."""
    interm = rewards + continues * values * (1.0 - lmbda)

    def step(carry, inp):
        i_t, c_t = inp
        v = i_t + c_t * lmbda * carry
        return v, v

    _, out = jax.lax.scan(step, values[-1], (interm, continues), reverse=True)
    return out


def two_hot(
    x: jax.Array, bins: jax.Array
) -> jax.Array:
    """Two-hot encoding of scalar targets over a monotonic bin support
    (DreamerV3, /root/reference/sheeprl/utils/distribution.py:220-266).

    x: [...] scalars; bins: [K]. Returns [..., K] with mass split between the
    two neighboring bins, all weight on an edge bin when out of range.
    """
    k = bins.shape[0]
    below = jnp.sum((bins <= x[..., None]).astype(jnp.int32), axis=-1) - 1
    above = k - jnp.sum((bins > x[..., None]).astype(jnp.int32), axis=-1)
    below = jnp.clip(below, 0, k - 1)
    above = jnp.clip(above, 0, k - 1)
    equal = below == above
    dist_to_below = jnp.where(equal, 1.0, jnp.abs(bins[below] - x))
    dist_to_above = jnp.where(equal, 1.0, jnp.abs(bins[above] - x))
    total = dist_to_below + dist_to_above
    w_below = dist_to_above / total
    w_above = dist_to_below / total
    target = (
        jax.nn.one_hot(below, k) * w_below[..., None]
        + jax.nn.one_hot(above, k) * w_above[..., None]
    )
    return target


def normalize(x: jax.Array, eps: float = 1e-8, mask: jax.Array | None = None):
    """(x - mean) / (std + eps), statistics over masked entries
    (/root/reference/sheeprl/utils/utils.py:106-112)."""
    if mask is None:
        mean, std = x.mean(), x.std()
    else:
        mask = mask.astype(jnp.float32)
        n = jnp.maximum(mask.sum(), 1.0)
        mean = (x * mask).sum() / n
        var = (jnp.square(x - mean) * mask).sum() / n
        std = jnp.sqrt(var)
    return (x - mean) / (std + eps)


def polynomial_decay(
    current_step: int,
    *,
    initial: float = 1.0,
    final: float = 0.0,
    max_decay_steps: int = 100,
    power: float = 1.0,
) -> float:
    """Host-side schedule helper (/root/reference/sheeprl/utils/utils.py:114-125)."""
    if current_step > max_decay_steps or initial == final:
        return final
    return (initial - final) * ((1 - current_step / max_decay_steps) ** power) + final
