"""Scan-unroll control: trace-time knob + a measured per-jit autotuner
(ISSUE 9 tentpole c).

The Dreamer-family train step is dominated by sequential scans with TINY
step bodies (RSSM dynamic: T=64 steps of [B=16]-row matmuls through
512-wide layers; imagination: horizon 15 of the same shapes). XLA lowers
`lax.scan` to a while-loop with per-iteration control overhead that rivals
the step's compute at these shapes, so modest unrolls (4-8) can win real
throughput — at the cost of compile time and code size. That trade is
hardware- and shape-dependent, which is why it was a knob with a bench
keep-decision (BENCHES.md round 4, hypothesis #2) rather than a hardcoded
value.

This module grows the knob into a measured ladder:

  - `scan_unroll()` stays the trace-time read (Pallas-switch style): the
    process-global override (autotuner / `unroll()` context) wins, then the
    `SHEEPRL_TPU_SCAN_UNROLL` env var, then 1.
  - `SHEEPRL_TPU_SCAN_UNROLL=auto` arms the autotuner: the dreamer mains
    call `autotune_unroll` on their RSSM scan with the run's EXACT shapes
    before tracing the train step. For each rung in `RUNGS` the scan is
    AOT-compiled (`jit.lower().compile()` — the PR-5 trial-compile
    machinery) and executed `repeats` times; the fastest rung wins and is
    installed as the process override, and every rung carries a
    BIT-EXACTNESS receipt vs rung 1 (unrolling reorders nothing — a rung
    that fails the receipt is disqualified, never silently kept).
  - winners persist NEXT TO the compile cache (`scan_unroll.json` in the
    jax compilation-cache directory, compile/cache.py): a re-run with the
    same (name, avals, jax version, backend) key skips the ladder and
    reuses the measured winner, exactly like a warm compile cache skips the
    compile.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Callable, Sequence

__all__ = [
    "RUNGS",
    "UnrollDecision",
    "autotune_unroll",
    "scan_unroll",
    "set_unroll",
    "unroll",
    "unroll_mode",
]

RUNGS = (1, 4, 8, 16, 32)

_OVERRIDE: int | None = None


def unroll_mode() -> str:
    """The env knob's raw mode: 'auto' (measured ladder), 'env' (a fixed
    integer is set), or 'off' (unset/default)."""
    raw = os.environ.get("SHEEPRL_TPU_SCAN_UNROLL", "").strip().lower()
    if raw == "auto":
        return "auto"
    if raw:
        return "env"
    return "off"


def scan_unroll() -> int:
    """Unroll factor for the framework's time/horizon scans (default 1 =
    plain while-loop). Read at trace time like the Pallas kernel switches:
    the autotuner's installed winner (or an `unroll()` context) takes
    precedence, then `SHEEPRL_TPU_SCAN_UNROLL=k`; `lax.scan` handles
    non-divisible lengths."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    try:
        return max(1, int(os.environ.get("SHEEPRL_TPU_SCAN_UNROLL", "1")))
    except ValueError:
        return 1


def set_unroll(k: int | None) -> None:
    """Install (or clear, with None) the process-global unroll override —
    what the autotuner does with the measured winner."""
    global _OVERRIDE
    _OVERRIDE = None if k is None else max(1, int(k))


@contextlib.contextmanager
def unroll(k: int | None):
    """Scoped override: trace/compile under a specific rung, then restore."""
    global _OVERRIDE
    prev = _OVERRIDE
    _OVERRIDE = None if k is None else max(1, int(k))
    try:
        yield
    finally:
        _OVERRIDE = prev


@dataclasses.dataclass
class UnrollDecision:
    """One measured ladder: per-rung compile/exec seconds, per-rung
    bit-exactness receipts vs rung 1, and the winner."""

    name: str
    winner: int
    timings: dict[int, float]  # rung -> median exec seconds
    compile_seconds: dict[int, float]  # rung -> AOT compile seconds
    bit_exact: dict[int, bool]  # rung -> outputs identical to rung 1
    source: str  # "measured" | "cache" | "env"
    key: str

    def as_event(self) -> dict[str, Any]:
        # "probe", not "name": the payload rides telemetry.event(name=...)
        return {
            "probe": self.name,
            "winner": int(self.winner),
            "timings_s": {str(k): v for k, v in self.timings.items()},
            "compile_s": {str(k): v for k, v in self.compile_seconds.items()},
            "bit_exact": {str(k): bool(v) for k, v in self.bit_exact.items()},
            "source": self.source,
        }

    def as_dict(self) -> dict[str, Any]:
        return {**self.as_event(), "key": self.key}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "UnrollDecision":
        return cls(
            name=d.get("probe") or d.get("name", ""),
            winner=int(d["winner"]),
            timings={int(k): float(v) for k, v in d.get("timings_s", {}).items()},
            compile_seconds={
                int(k): float(v) for k, v in d.get("compile_s", {}).items()
            },
            bit_exact={int(k): bool(v) for k, v in d.get("bit_exact", {}).items()},
            source="cache",
            key=d.get("key", ""),
        )


def _store_path(explicit: str | None = None) -> str:
    """The winner store lives next to the persistent compile cache — same
    resolution order as compile/cache.py, without arming anything."""
    if explicit:
        return explicit
    base = (
        os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    )
    if not base:
        from ..compile.cache import default_cache_dir

        base = default_cache_dir()
    return os.path.join(base, "scan_unroll.json")


def _load_store(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except Exception:
        return {}


def _save_store(path: str, store: dict) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(store, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # the store is an optimization; never fail the run on it


def _decision_key(name: str, example: Sequence[Any]) -> str:
    import jax

    avals = ",".join(
        f"{getattr(getattr(a, 'dtype', None), 'name', type(a).__name__)}"
        f"{list(getattr(a, 'shape', []))}"
        for a in jax.tree_util.tree_leaves(example)
    )
    return f"{name}|{avals}|jax{jax.__version__}|{jax.default_backend()}"


def _bit_exact(a: Any, b: Any) -> bool:
    import jax
    import numpy as np

    la = [np.asarray(x) for x in jax.tree_util.tree_leaves(a)]
    lb = [np.asarray(x) for x in jax.tree_util.tree_leaves(b)]
    if len(la) != len(lb):
        return False
    return all(np.array_equal(x, y, equal_nan=True) for x, y in zip(la, lb))


def autotune_unroll(
    name: str,
    fn: Callable,
    example: Sequence[Any],
    *,
    rungs: Sequence[int] = RUNGS,
    repeats: int = 3,
    store_path: str | None = None,
    force: bool = False,
    apply: bool = True,
) -> UnrollDecision:
    """Measure the unroll ladder for one scan-bearing function and return
    (and by default install) the winner.

    `fn(*example)` must be jittable and contain scans whose `unroll=` reads
    `scan_unroll()` at trace time. Per rung: AOT `lower().compile()` (so
    compile time is measured apart from exec), one untimed warm-up call,
    then `repeats` timed calls (median). Rung 1 is the reference: any rung
    whose outputs are not bit-identical is disqualified. The winner is the
    fastest surviving rung; ties break toward the SMALLER rung (less code).
    """
    import jax

    path = _store_path(store_path)
    key = _decision_key(name, example)
    if not force:
        store = _load_store(path)
        hit = store.get(key)
        if hit:
            decision = UnrollDecision.from_dict({**hit, "key": key})
            if apply:
                set_unroll(decision.winner)
            return decision

    timings: dict[int, float] = {}
    compile_seconds: dict[int, float] = {}
    bit_exact: dict[int, bool] = {}
    outputs: dict[int, Any] = {}
    rungs = list(dict.fromkeys(int(r) for r in rungs))
    if 1 not in rungs:
        rungs.insert(0, 1)
    # throwaway lower + trivial compile: absorb the process's one-time
    # tracing/MLIR/LLVM-backend warmup so it doesn't bias the first rung's
    # compile_seconds (the same first-call attribution trap as the r4/r5
    # compile-vs-exec mixup)
    import jax.numpy as jnp

    def fresh(_rung):
        # a NEW callable per rung: jax caches traces by function identity,
        # so re-jitting the same `fn` under a different unroll context
        # would silently reuse rung 1's jaxpr and the whole ladder would
        # measure one program five times
        return lambda *a: fn(*a)

    with unroll(rungs[0]):
        jax.jit(fresh(0)).lower(*example)
    jax.block_until_ready(jax.jit(lambda v: v + 1.0)(jnp.float32(0.0)))
    for rung in rungs:
        with unroll(rung):
            t0 = time.perf_counter()
            # sheeplint: disable=SL004 — a fresh jit per rung is the POINT:
            # each rung must trace its own program (jax's trace cache keys
            # on fn identity; reusing one jit would measure rung 1 five
            # times), and the ladder runs once per (shape, backend) key
            compiled = jax.jit(fresh(rung)).lower(*example).compile()
            compile_seconds[rung] = time.perf_counter() - t0
            out = jax.block_until_ready(compiled(*example))  # warm-up
            samples = []
            for _ in range(max(1, repeats)):
                t0 = time.perf_counter()
                out = jax.block_until_ready(compiled(*example))
                samples.append(time.perf_counter() - t0)
            samples.sort()
            timings[rung] = samples[len(samples) // 2]
            outputs[rung] = out
    reference = outputs[1]
    for rung in rungs:
        bit_exact[rung] = True if rung == 1 else _bit_exact(reference, outputs[rung])
    eligible = [r for r in rungs if bit_exact[r]]
    winner = min(eligible, key=lambda r: (timings[r], r))
    decision = UnrollDecision(
        name=name,
        winner=winner,
        timings=timings,
        compile_seconds=compile_seconds,
        bit_exact=bit_exact,
        source="measured",
        key=key,
    )
    store = _load_store(path)
    store[key] = decision.as_dict()
    _save_store(path, store)
    if apply:
        set_unroll(winner)
    return decision
