"""Scan-unroll control: trace-time knob + a measured per-jit autotuner.

The Dreamer-family train step is dominated by sequential scans with TINY
step bodies (RSSM dynamic: T=64 steps of [B=16]-row matmuls through
512-wide layers; imagination: horizon 15 of the same shapes). XLA lowers
`lax.scan` to a while-loop with per-iteration control overhead that rivals
the step's compute at these shapes, so modest unrolls (4-8) can win real
throughput — at the cost of compile time and code size. That trade is
hardware- and shape-dependent, which is why it is measured, not hardcoded.

  - `scan_unroll()` is the trace-time read (Pallas-switch style): the
    process-global override (autotuner / `unroll()` context) wins, then the
    `SHEEPRL_TPU_SCAN_UNROLL` env var, then 1.
  - `SHEEPRL_TPU_SCAN_UNROLL=auto` arms the autotuner: the dreamer mains
    call `autotune_unroll` on their RSSM scan with the run's EXACT shapes
    before tracing the train step.

Since ISSUE 11 the ladder itself — per-rung AOT `lower().compile()`,
exec timing, BIT-EXACTNESS receipts vs rung 1, winner persistence — is
the unified measured-decision framework (`compile/decisions.py`, knob
family `scan_unroll`): winners live in the ONE decision cache next to the
compile cache (`decisions.json`) instead of the pre-ISSUE-11 private
`scan_unroll.json`, whose entries are one-shot migrated on first use.
`UnrollDecision` remains this module's typed view of the decision.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Any, Callable, Sequence

__all__ = [
    "RUNGS",
    "UnrollDecision",
    "autotune_unroll",
    "checkpoint_body",
    "scan_unroll",
    "set_unroll",
    "unroll",
    "unroll_mode",
]


def checkpoint_body(step: Callable, remat: Any) -> Callable:
    """The ONE place a scan body is wrapped for rematerialization, shared
    by every dreamer-family RSSM/imagination scan. `remat` is the settled
    mode (`compile.decisions.remat_mode`): "on" (or legacy True) = full
    `jax.checkpoint` — store only the carry, recompute the whole step on
    backward; "policy" = checkpoint with
    `dots_with_no_batch_dims_saveable` — matmul outputs stay saved, only
    the cheap elementwise ops recompute (most of full remat's byte
    savings at near-zero exec cost, the rung the sheepopt ladder usually
    accepts on exec-bound hosts); anything else = `step` unchanged.
    `prevent_cse=False` throughout: under `lax.scan` the loop-carried
    dependence already blocks the CSE that flag guards against."""
    import jax

    mode = remat if isinstance(remat, str) else ("on" if remat else "off")
    mode = mode.strip().lower()
    if mode == "policy":
        return jax.checkpoint(
            step,
            prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    if mode in ("on", "true", "1", "yes"):
        return jax.checkpoint(step, prevent_cse=False)
    return step

RUNGS = (1, 4, 8, 16, 32)

_OVERRIDE: int | None = None


def unroll_mode() -> str:
    """The env knob's raw mode: 'auto' (measured ladder), 'env' (a fixed
    integer is set), or 'off' (unset/default)."""
    raw = os.environ.get("SHEEPRL_TPU_SCAN_UNROLL", "").strip().lower()
    if raw == "auto":
        return "auto"
    if raw:
        return "env"
    return "off"


def scan_unroll() -> int:
    """Unroll factor for the framework's time/horizon scans (default 1 =
    plain while-loop). Read at trace time like the Pallas kernel switches:
    the autotuner's installed winner (or an `unroll()` context) takes
    precedence, then `SHEEPRL_TPU_SCAN_UNROLL=k`; `lax.scan` handles
    non-divisible lengths."""
    if _OVERRIDE is not None:
        return _OVERRIDE
    try:
        return max(1, int(os.environ.get("SHEEPRL_TPU_SCAN_UNROLL", "1")))
    except ValueError:
        return 1


def set_unroll(k: int | None) -> None:
    """Install (or clear, with None) the process-global unroll override —
    what the autotuner does with the measured winner."""
    global _OVERRIDE
    _OVERRIDE = None if k is None else max(1, int(k))


@contextlib.contextmanager
def unroll(k: int | None):
    """Scoped override: trace/compile under a specific rung, then restore."""
    global _OVERRIDE
    prev = _OVERRIDE
    _OVERRIDE = None if k is None else max(1, int(k))
    try:
        yield
    finally:
        _OVERRIDE = prev


@dataclasses.dataclass
class UnrollDecision:
    """One measured ladder: per-rung compile/exec seconds, per-rung
    bit-exactness receipts vs rung 1, and the winner. A typed view of the
    unified `compile/decisions.py` Decision for the scan_unroll family."""

    name: str
    winner: int
    timings: dict[int, float]  # rung -> median exec seconds
    compile_seconds: dict[int, float]  # rung -> AOT compile seconds
    bit_exact: dict[int, bool]  # rung -> outputs identical to rung 1
    source: str  # "measured" | "cache" | "env"
    key: str

    def as_event(self) -> dict[str, Any]:
        # "probe", not "name": the payload rides telemetry.event(name=...)
        return {
            "probe": self.name,
            "winner": int(self.winner),
            "timings_s": {str(k): v for k, v in self.timings.items()},
            "compile_s": {str(k): v for k, v in self.compile_seconds.items()},
            "bit_exact": {str(k): bool(v) for k, v in self.bit_exact.items()},
            "source": self.source,
        }

    def as_dict(self) -> dict[str, Any]:
        return {**self.as_event(), "key": self.key}

    @classmethod
    def from_decision(cls, decision: Any) -> "UnrollDecision":
        """Build the typed view from a `compile.decisions.Decision`."""
        timings: dict[int, float] = {}
        compile_s: dict[int, float] = {}
        bit_exact: dict[int, bool] = {}
        for label, rep in decision.candidates.items():
            rung = int(label)
            if rep.get("exec_seconds") is not None:
                timings[rung] = float(rep["exec_seconds"])
            if rep.get("compile_seconds") is not None:
                compile_s[rung] = float(rep["compile_seconds"])
            bit_exact[rung] = bool(rep.get("bit_exact"))
        return cls(
            name=decision.name,
            winner=int(decision.winner),
            timings=timings,
            compile_seconds=compile_s,
            bit_exact=bit_exact,
            source=decision.source,
            key=decision.key,
        )


def autotune_unroll(
    name: str,
    fn: Callable,
    example: Sequence[Any],
    *,
    rungs: Sequence[int] = RUNGS,
    repeats: int = 3,
    store_path: str | None = None,
    force: bool = False,
    apply: bool = True,
) -> UnrollDecision:
    """Measure the unroll ladder for one scan-bearing function and return
    (and by default install) the winner.

    `fn(*example)` must be jittable and contain scans whose `unroll=` reads
    `scan_unroll()` at trace time. The ladder rides the unified decision
    framework: per rung an AOT trial compile + timed execution + a
    bit-exactness receipt vs rung 1 (a non-bit-exact rung is disqualified);
    the winner is the fastest surviving rung, ties breaking toward the
    SMALLER rung (less code), and persists in the shared decision cache —
    a re-run with the same (name, avals, jax version, backend) key skips
    the whole ladder."""
    from ..compile import decisions as dec

    path = dec.cache_path(store_path)
    dec.migrate_legacy_scan_unroll(path)
    ladder = list(dict.fromkeys(int(r) for r in rungs))
    if 1 not in ladder:
        ladder.insert(0, 1)
    ladder.sort()  # rung 1 first (the baseline); ties break toward small
    decision = dec.decide(
        "scan_unroll",
        name,
        ladder,
        lambda _rung: (lambda *a: fn(*a)),
        example,
        objective="seconds",
        repeats=repeats,
        store_path=path,
        force=force,
        candidate_context=unroll,
    )
    result = UnrollDecision.from_decision(decision)
    if apply:
        set_unroll(result.winner)
    return result
