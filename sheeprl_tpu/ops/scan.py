"""Trace-time knob for `lax.scan` unrolling on the time/horizon recurrences.

The Dreamer-family train step is dominated by sequential scans with TINY
step bodies (RSSM dynamic: T=64 steps of [B=16]-row matmuls through
512-wide layers; imagination: horizon 15 of the same shapes). XLA lowers
`lax.scan` to a while-loop with per-iteration control overhead that rivals
the step's compute at these shapes, so modest unrolls (4-8) can win real
throughput — at the cost of compile time and code size, which is why the
factor is a knob with a bench keep-decision (BENCHES.md) rather than a
hardcoded value.

Read at trace time like the Pallas kernel switches
(`ops/pallas_kernels.py`): flipping `SHEEPRL_TPU_SCAN_UNROLL` between
measurements re-traces with the new factor.
"""

from __future__ import annotations

import os

__all__ = ["scan_unroll"]


def scan_unroll() -> int:
    """Unroll factor for the framework's time/horizon scans (default 1 =
    plain while-loop). Set `SHEEPRL_TPU_SCAN_UNROLL=k` to unroll k steps
    per loop iteration; `lax.scan` handles non-divisible lengths."""
    try:
        return max(1, int(os.environ.get("SHEEPRL_TPU_SCAN_UNROLL", "1")))
    except ValueError:
        return 1
