"""The ONE mixed-precision policy for every train step (ISSUE 9 tentpole a).

Before this module, `--precision bfloat16` existed only for the discrete-
latent Dreamer family, each main hand-rolling the same
``jnp.bfloat16 if args.precision == "bfloat16" else jnp.float32`` line and
its own cast sites — and `algos.args.require_float32` rejected the flag
everywhere else. This module centralizes the policy so all 13 mains share
one contract, the same one the software–hardware co-optimization toolkit
(arXiv:2311.09445) measures as the biggest single-chip lever after kernel
fusion:

  - **bf16 compute**: network forwards AND backwards (encoders, RSSM /
    LSTM recurrences, actor/critic trunks, imagination) run in bfloat16.
    The parameter story rides the dtype-following layer design
    (`nn/layers.py`: every layer casts its weights to the input dtype), so
    "run in bf16" means exactly "cast the inputs" — there is no second
    copy of the model.
  - **fp32 master params + optimizer moments**: parameters are created
    and stored in float32 and NEVER cast in place; the `convert` the
    layers insert is differentiable, so cotangents arrive back in f32 and
    optax moments/updates stay full width. Checkpoints therefore always
    hold fp32 master weights (`--precision bfloat16` round-trips exactly).
  - **fp32 islands**: loss reductions, logits/distribution math,
    return/advantage/Bellman math, KL and moments run in float32 — heads
    upcast with `to_float32` at the boundary. These are the *declared*
    upcasts the sheepcheck `--audit-bf16` ledger commits per jit
    (`bf16_upcasts` in `analysis/budget/`); a new silent upcast beyond the
    declared count fails CI.

All casts are no-ops when the policy is f32 (``jnp.astype`` returns the
operand unchanged when dtypes already match), so wiring a main through the
policy leaves its f32 jaxpr — and its committed budget fingerprint —
byte-identical.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ..nn.core import cast_floating

__all__ = ["Policy", "policy", "compute_dtype", "to_compute", "to_float32"]


def compute_dtype(precision: str) -> Any:
    """Map a StandardArgs `precision` string to the compute dtype."""
    if precision == "bfloat16":
        return jnp.bfloat16
    if precision == "float32":
        return jnp.float32
    raise ValueError(
        f"precision must be 'float32' or 'bfloat16', got {precision!r}"
    )


def to_compute(tree: Any, dtype: Any) -> Any:
    """Cast the floating leaves of `tree` to the compute dtype (ints, bools
    and uint8 pixels pass through; pixel normalization casts them itself).
    No-op when `dtype` is float32 and the leaves already are."""
    return cast_floating(tree, dtype)


def to_float32(tree: Any) -> Any:
    """Upcast head outputs / pre-loss values to the fp32 island. This is
    the DECLARED upcast of the mixed-precision contract: every call site
    is a loss/logit/return boundary the bf16 audit expects to see."""
    return cast_floating(tree, jnp.float32)


@dataclasses.dataclass(frozen=True)
class Policy:
    """The resolved mixed-precision policy of one run.

    `compute` is what forwards/backwards trace in; `param` / `reduce` are
    fixed at float32 by design (master weights, moments, losses). The
    object is cheap and hashable — mains build it once in
    `make_train_step` and close over it."""

    compute: Any
    param: Any = jnp.float32
    reduce: Any = jnp.float32

    @property
    def mixed(self) -> bool:
        return jnp.dtype(self.compute) != jnp.dtype(self.param)

    # -- cast helpers (all no-ops under the f32 policy) ---------------------
    def cast_in(self, tree: Any) -> Any:
        """Inputs entering the network trunk -> compute dtype."""
        return cast_floating(tree, self.compute)

    def cast_out(self, tree: Any) -> Any:
        """Head outputs leaving the trunk -> the fp32 island."""
        return cast_floating(tree, self.reduce)

    def zeros(self, shape: tuple[int, ...]) -> jax.Array:
        """Recurrent/carry initializers in the compute dtype (a stray f32
        carry would promote the whole recurrence back to full width)."""
        return jnp.zeros(shape, self.compute)

    @property
    def name(self) -> str:
        return jnp.dtype(self.compute).name


def policy(precision: str) -> Policy:
    """Resolve a StandardArgs `precision` string into the shared Policy."""
    return Policy(compute=compute_dtype(precision))
