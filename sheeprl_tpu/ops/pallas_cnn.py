"""Pallas TPU kernels for the DreamerV3 CNN encoder/decoder stages — the
fourth north-star kernel family (BASELINE.md; reference hot path
/root/reference/sheeprl/algos/dreamer_v3/agent.py:31-203 and
/root/reference/sheeprl/models/models.py:121-284).

Encoder stage = Conv2d(k4, s2, SAME, no bias) -> LayerNorm(C) -> SiLU.
Decoder stage = ConvTranspose2d(k4, s2, SAME, no bias) -> LayerNorm(C) -> SiLU,
computed in the subpixel formulation (dense 2x2 conv + depth-to-space, the
same regrouping as nn.layers.ConvTranspose2d._subpixel_k4s2).

What the fusion buys: the conv pre-activation, the LayerNorm moments and the
SiLU stay entirely in VMEM — XLA stages the conv output through HBM before
the channel-reduction LayerNorm can run.

Kernel shape discipline (learned against real-Mosaic, not interpret mode):
strided vector slices, concatenation of offset slices, minor-dim slicing and
non-tile-aligned reshapes are all rejected or fragile in Mosaic, so the
kernels see only 2-D row-block matmuls and leading-axis indexing:

  - the caller space-to-depth-packs the padded input (k4/s2 -> k2/s1 over
    phases) and pre-flattens the four 2x2-window tap matrices to
    [rows, Cin'] in XLA;
  - the kernel computes the conv as a sum of four 2-D matmuls (one per
    tap; weights arrive as leading-indexed [4|16, Cin', Cout] blocks),
    then LayerNorm+SiLU on the [rows, Cout] block;
  - for the decoder, LN/SiLU apply per-phase (each output pixel maps to
    exactly one phase, LN is per-pixel over channels), and the subpixel
    interleave happens XLA-side after the kernel.

Differentiation follows the GRU kernel's policy (pallas_kernels.py): the
forward-with-residuals kernel additionally emits the raw conv
pre-activation; the backward is plain XLA — it recomputes the LN stats
from the pre-activation with the forward's exact ops, then elementwise
LN/SiLU math plus XLA's own conv VJP for dx/dW — so training numerics are
exactly those of the unfused path.

Keep-decision: bench.py measures duty cycles with the family toggled via
SHEEPRL_TPU_PALLAS_CNN and keeps the winner, like every other family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_kernels import _VMEM, _cdiv, _interpret_mode, use_pallas

__all__ = ["conv_ln_silu", "deconv_ln_silu", "cnn_stage_supported"]


# rows of conv output aimed at one grid step (M dimension of the MXU matmul)
_ROWS_BLOCK = 2048
# VMEM budget for one grid step's tap + output blocks (bytes); Mosaic's
# scoped-vmem limit is 16 MiB and blocks are double-buffered across steps
_VMEM_ROW_BUDGET = 4 * 1024 * 1024


def _pad128(c: int) -> int:
    return -(-c // 128) * 128


def _pick_blk(rows: int, row_bytes: int) -> int:
    """Row-block size: target _ROWS_BLOCK, shrink to the VMEM budget
    (row_bytes = f32 bytes per row across all tap and output blocks,
    lane-padding included), keep a sublane multiple."""
    blk = min(rows, _ROWS_BLOCK, max(_VMEM_ROW_BUDGET // max(row_bytes, 1), 8))
    return max(8 * (blk // 8), min(rows, 8))


def cnn_stage_supported(kernel_shape, stride, padding, has_norm, act) -> bool:
    """Eligibility for the fused stage: the Dreamer k4/s2/SAME LayerNorm-SiLU
    miniblock exactly (callers fall back to plain XLA otherwise)."""
    return (
        use_pallas("cnn")
        and tuple(kernel_shape[:2]) == (4, 4)
        and tuple(stride) == (2, 2)
        and padding == "SAME"
        and has_norm
        and act == "silu"
    )


def _silu(z):
    return z * jax.nn.sigmoid(z)


def _ln_stats(pre, eps):
    """LN normalized activations + inverse stddev — the ONE definition both
    the forward kernels and the XLA backward recompute from, so their
    numerics cannot de-sync."""
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    centered = pre - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return centered * rstd, rstd


def _ln_silu(pre, scale, offset, eps):
    """LayerNorm + SiLU on a [rows, C] block, f32 moments."""
    hat, _ = _ln_stats(pre, eps)
    return _silu(hat * scale + offset)


# =============================================================================
# encoder stage: conv k4/s2/SAME + LayerNorm + SiLU
# =============================================================================


def _enc_kernel(t0, t1, t2, t3, w_ref, scale_ref, offset_ref, y_ref, *, eps,
                residuals=False, pre_ref=None):
    """One [rows, Cout] block: sum of four 2-D tap matmuls + LN + SiLU.
    With residuals, the raw pre-activation is the single saved tensor (the
    backward recomputes the LN stats from it — one output instead of a
    [rows, Cout] + a 128-lane-padded [rows, 1])."""
    pre = None
    for uv, tap in enumerate((t0, t1, t2, t3)):
        d = jnp.dot(tap[:], w_ref[uv], preferred_element_type=jnp.float32)
        pre = d if pre is None else pre + d
    y_ref[:] = _ln_silu(pre, scale_ref[:], offset_ref[:], eps).astype(y_ref.dtype)
    if residuals:
        pre_ref[:] = pre


def _enc_taps(x):
    """Pad for SAME k4/s2, space-to-depth-pack the 2x2 phases into channels
    (k4/s2 -> k2/s1 over the phase grid), and flatten the four 2x2-window
    taps to [N*Ho*Wo, 4*Cin] row matrices — all XLA-side."""
    n, h, w, cin = x.shape
    ho, wo = h // 2, w // 2
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    # H+2 = 2*(ho+1): the padded grid splits into phases exactly
    xp = (
        xp.reshape(n, ho + 1, 2, wo + 1, 2, cin)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(n, ho + 1, wo + 1, 4 * cin)
    )
    return [
        jax.lax.slice(xp, (0, u, v, 0), (n, u + ho, v + wo, 4 * cin)).reshape(
            n * ho * wo, 4 * cin
        )
        for u in range(2)
        for v in range(2)
    ]


def _enc_call(x, w3, scale, offset, eps, residuals):
    n, h, w, cin = x.shape
    ho, wo = h // 2, w // 2
    cout = w3.shape[-1]
    taps = _enc_taps(x)
    rows = n * ho * wo
    itemsize = 2 if x.dtype == jnp.bfloat16 else 4
    row_bytes = (
        4 * _pad128(4 * cin) * itemsize  # taps
        + _pad128(cout) * itemsize  # y
        + residuals * _pad128(cout) * 4  # saved pre-activation (f32)
    )
    blk = _pick_blk(rows, row_bytes)
    tap_spec = pl.BlockSpec((blk, 4 * cin), lambda i: (i, 0), memory_space=_VMEM)
    out_shape = [jax.ShapeDtypeStruct((rows, cout), x.dtype)]
    out_specs = [pl.BlockSpec((blk, cout), lambda i: (i, 0), memory_space=_VMEM)]
    if residuals:
        out_shape.append(jax.ShapeDtypeStruct((rows, cout), jnp.float32))
        out_specs.append(
            pl.BlockSpec((blk, cout), lambda i: (i, 0), memory_space=_VMEM)
        )
    kernel = functools.partial(_enc_kernel, eps=eps, residuals=residuals)
    if residuals:
        body = lambda a, b, c, d, wr, sr, or_, yr, pr: kernel(
            a, b, c, d, wr, sr, or_, yr, pre_ref=pr
        )
    else:
        body = kernel
    out = pl.pallas_call(
        body,
        grid=(_cdiv(rows, blk),),
        out_shape=tuple(out_shape) if residuals else out_shape[0],
        in_specs=[tap_spec] * 4
        + [
            pl.BlockSpec(w3.shape, lambda i: (0, 0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=tuple(out_specs) if residuals else out_specs[0],
        interpret=_interpret_mode(),
    )(*taps, w3, scale, offset)
    if residuals:
        y, pre = out
        return y.reshape(n, ho, wo, cout), pre.reshape(n, ho, wo, cout)
    return out.reshape(n, ho, wo, cout)


def _enc_w3(w):
    """[4, 4, Cin, Cout] conv kernel -> [4, 4*Cin, Cout] leading-indexed tap
    blocks matching _enc_taps' layout: tap (u, v) outer, space-to-depth
    phase (a, b) + channel minor (kh = 2u+a, kw = 2v+b)."""
    cin, cout = w.shape[2], w.shape[3]
    kk = w.reshape(2, 2, 2, 2, cin, cout)  # [u, a, v, b, cin, cout]
    return kk.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4 * cin, cout)


def _enc_conv(x, w):
    """The bare conv (XLA) — its VJP supplies dx/dW in the backward."""
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(2, 2),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _ln_silu_bwd(dy, pre, scale, offset, eps):
    """Grad of SiLU(LayerNorm(pre)) wrt pre / scale / offset. Recomputes the
    LN stats from the saved pre-activation via the forward's _ln_stats."""
    dy = dy.astype(jnp.float32)
    hat, rstd = _ln_stats(pre, eps)
    z = hat * scale + offset
    sig = jax.nn.sigmoid(z)
    dz = dy * (sig * (1.0 + z * (1.0 - sig)))  # SiLU'
    dscale = jnp.sum(dz * hat, axis=tuple(range(dz.ndim - 1)))
    doffset = jnp.sum(dz, axis=tuple(range(dz.ndim - 1)))
    g = dz * scale
    dpre = rstd * (
        g
        - jnp.mean(g, axis=-1, keepdims=True)
        - hat * jnp.mean(g * hat, axis=-1, keepdims=True)
    )
    return dpre, dscale, doffset


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def conv_ln_silu(x, w, scale, offset, eps=1e-3):
    """Fused Dreamer encoder stage. x: [N, H, W, Cin] (H, W even),
    w: [4, 4, Cin, Cout] conv kernel, scale/offset: LayerNorm affine."""
    return _enc_call(x, _enc_w3(w), scale, offset, eps, False)


def _conv_ln_silu_fwd(x, w, scale, offset, eps):
    y, pre = _enc_call(x, _enc_w3(w), scale, offset, eps, True)
    return y, (x, w, scale, offset, pre)


def _conv_ln_silu_bwd(eps, res, dy):
    x, w, scale, offset, pre = res
    dpre, dscale, doffset = _ln_silu_bwd(dy, pre, scale, offset, eps)
    _, conv_vjp = jax.vjp(_enc_conv, x, w)
    dx, dw = conv_vjp(dpre.astype(x.dtype))
    return dx, dw.astype(w.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


conv_ln_silu.defvjp(_conv_ln_silu_fwd, _conv_ln_silu_bwd)


# =============================================================================
# decoder stage: subpixel deconv k4/s2/SAME + LayerNorm + SiLU
# =============================================================================


def _dec_kernel(t0, t1, t2, t3, w_ref, scale_ref, offset_ref, y_ref, *, eps,
                residuals=False, pre_ref=None):
    """Four output phases, each a sum of four 2-D tap matmuls + LN + SiLU
    (w_ref: [16, Cin, Cout] blocks indexed p*4 + ab). LN/SiLU apply in
    phase layout — each output pixel maps to exactly one phase — and the
    subpixel interleave happens XLA-side after."""
    taps = (t0[:], t1[:], t2[:], t3[:])
    for p in range(4):  # output phase (dh, dw) = divmod(p, 2)
        pre = None
        for ab in range(4):
            d = jnp.dot(
                taps[ab], w_ref[p * 4 + ab], preferred_element_type=jnp.float32
            )
            pre = d if pre is None else pre + d
        y_ref[p] = _ln_silu(pre, scale_ref[:], offset_ref[:], eps).astype(
            y_ref.dtype
        )
        if residuals:
            pre_ref[p] = pre


def _dec_taps(x):
    """Pad and flatten the four 2x2-window taps of the dense phase conv to
    [N*(H+1)*(W+1), Cin] row matrices — all XLA-side."""
    n, h, w, cin = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    return [
        jax.lax.slice(xp, (0, a, b, 0), (n, a + h + 1, b + w + 1, cin)).reshape(
            n * (h + 1) * (w + 1), cin
        )
        for a in range(2)
        for b in range(2)
    ]


def _interleave_phases(ph, n, h, w):
    """[4, N*(h+1)*(w+1), C] phase rows -> [N, 2h, 2w, C] subpixel output
    (phase p = dh*2+dw; same selection as ConvTranspose2d._subpixel_k4s2)."""
    c = ph.shape[-1]
    ph = ph.reshape(4, n, h + 1, w + 1, c)
    row0 = jnp.stack([ph[0][:, :h, :w], ph[1][:, :h, 1:]], axis=3)
    row1 = jnp.stack([ph[2][:, 1:, :w], ph[3][:, 1:, 1:]], axis=3)
    return jnp.stack([row0, row1], axis=2).reshape(n, 2 * h, 2 * w, c)


def _dec_call(x, w3, scale, offset, eps, residuals):
    n, h, w, cin = x.shape
    cout = w3.shape[-1]
    taps = _dec_taps(x)
    rows = n * (h + 1) * (w + 1)
    itemsize = 2 if x.dtype == jnp.bfloat16 else 4
    row_bytes = 4 * _pad128(cin) * itemsize + 4 * _pad128(cout) * (
        itemsize + 4 * residuals
    )
    blk = _pick_blk(rows, row_bytes)
    tap_spec = pl.BlockSpec((blk, cin), lambda i: (i, 0), memory_space=_VMEM)
    out_shape = [jax.ShapeDtypeStruct((4, rows, cout), x.dtype)]
    out_specs = [
        pl.BlockSpec((4, blk, cout), lambda i: (0, i, 0), memory_space=_VMEM)
    ]
    if residuals:
        out_shape.append(jax.ShapeDtypeStruct((4, rows, cout), jnp.float32))
        out_specs.append(
            pl.BlockSpec((4, blk, cout), lambda i: (0, i, 0), memory_space=_VMEM)
        )
    kernel = functools.partial(_dec_kernel, eps=eps, residuals=residuals)
    if residuals:
        body = lambda a, b, c, d, wr, sr, or_, yr, pr: kernel(
            a, b, c, d, wr, sr, or_, yr, pre_ref=pr
        )
    else:
        body = kernel
    out = pl.pallas_call(
        body,
        grid=(_cdiv(rows, blk),),
        out_shape=tuple(out_shape) if residuals else out_shape[0],
        in_specs=[tap_spec] * 4
        + [
            pl.BlockSpec(w3.shape, lambda i: (0, 0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=tuple(out_specs) if residuals else out_specs[0],
        interpret=_interpret_mode(),
    )(*taps, w3, scale, offset)
    if residuals:
        y, pre = out
        return _interleave_phases(y, n, h, w), _interleave_phases(pre, n, h, w)
    return _interleave_phases(out, n, h, w)


def _dec_wmat(k):
    """[4, 4, Cin, Cout] transposed-conv kernel -> [4*Cin, 4*Cout] dense 2x2
    phase matrix, ordering matched to _dec_deconv's cols/phases (identical to
    ConvTranspose2d._subpixel_k4s2's regrouping)."""
    cin, cout = k.shape[2], k.shape[3]
    kk = k.reshape(2, 2, 2, 2, cin, cout)  # [a, dh, b, dw, cin, cout]
    return kk.transpose(0, 2, 4, 1, 3, 5).reshape(4 * cin, 4 * cout)


def _dec_w3(k):
    """[4, 4, Cin, Cout] transposed-conv kernel -> [16, Cin, Cout] blocks
    indexed p*4 + ab (p = output phase dh*2+dw, ab = tap a*2+b) — the
    leading-indexed layout _dec_kernel consumes (no minor-dim slicing)."""
    cin, cout = k.shape[2], k.shape[3]
    kk = k.reshape(2, 2, 2, 2, cin, cout)  # [a, dh, b, dw, cin, cout]
    return kk.transpose(1, 3, 0, 2, 4, 5).reshape(16, cin, cout)


def _dec_deconv(x, k):
    """The bare transposed conv (XLA subpixel formulation) — VJP source for
    the backward."""
    n, h, w, cin = x.shape
    cout = k.shape[3]
    kk = _dec_wmat(k.astype(x.dtype)).reshape(2, 2, cin, 4 * cout)
    ph = jax.lax.conv_general_dilated(
        x, kk, window_strides=(1, 1), padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ).reshape(n, h + 1, w + 1, 2, 2, cout)
    row0 = jnp.stack([ph[:, :h, :w, 0, 0], ph[:, :h, 1:, 0, 1]], axis=3)
    row1 = jnp.stack([ph[:, 1:, :w, 1, 0], ph[:, 1:, 1:, 1, 1]], axis=3)
    return jnp.stack([row0, row1], axis=2).reshape(n, 2 * h, 2 * w, cout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def deconv_ln_silu(x, k, scale, offset, eps=1e-3):
    """Fused Dreamer decoder stage. x: [N, H, W, Cin],
    k: [4, 4, Cin, Cout] transposed-conv kernel, scale/offset: LN affine."""
    return _dec_call(x, _dec_w3(k), scale, offset, eps, False)


def _deconv_ln_silu_fwd(x, k, scale, offset, eps):
    y, pre = _dec_call(x, _dec_w3(k), scale, offset, eps, True)
    return y, (x, k, scale, offset, pre)


def _deconv_ln_silu_bwd(eps, res, dy):
    x, k, scale, offset, pre = res
    dpre, dscale, doffset = _ln_silu_bwd(dy, pre, scale, offset, eps)
    _, vjp = jax.vjp(_dec_deconv, x, k)
    dx, dk = vjp(dpre.astype(x.dtype))
    return dx, dk.astype(k.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


deconv_ln_silu.defvjp(_deconv_ln_silu_fwd, _deconv_ln_silu_bwd)
