"""Pallas TPU kernels for the DreamerV3 CNN encoder/decoder stages — the
fourth north-star kernel family (BASELINE.md; reference hot path
/root/reference/sheeprl/algos/dreamer_v3/agent.py:31-203 and
/root/reference/sheeprl/models/models.py:121-284).

Encoder stage = Conv2d(k4, s2, SAME, no bias) -> LayerNorm(C) -> SiLU.
Decoder stage = ConvTranspose2d(k4, s2, SAME, no bias) -> LayerNorm(C) -> SiLU,
computed in the subpixel formulation (dense 2x2 conv + depth-to-space, the
same regrouping as nn.layers.ConvTranspose2d._subpixel_k4s2).

What the fusion buys: one kernel per stage keeps the im2col patch matrix,
the conv pre-activation, the LayerNorm moments and the SiLU entirely in
VMEM — XLA stages the conv output through HBM before the channel-reduction
LayerNorm can run. The convolution itself becomes a single MXU matmul
(strided parity slices build the patch matrix in registers; for s=2 every
input pixel appears in exactly 4 patches, so the patch matrix is 4x the
input — it lives and dies inside VMEM).

Differentiation follows the GRU kernel's policy (pallas_kernels.py): the
forward-with-residuals kernel additionally emits the normalized activations
and inverse stddev; the backward is plain XLA — elementwise LN/SiLU math
from the residuals plus XLA's own conv VJP for dx/dW — so training numerics
are exactly those of the unfused path.

Keep-decision: bench.py measures duty cycles with the family toggled via
SHEEPRL_TPU_PALLAS_CNN and keeps the winner, like every other family.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_kernels import _VMEM, _cdiv, _interpret_mode, use_pallas

__all__ = ["conv_ln_silu", "deconv_ln_silu", "cnn_stage_supported"]


# pixels of conv output aimed at one grid step (M dimension of the MXU
# matmul); the batch tile adapts so bn * ho * wo stays near this
_ROWS_TARGET = 2048


def cnn_stage_supported(kernel_shape, stride, padding, has_norm, act) -> bool:
    """Eligibility for the fused stage: the Dreamer k4/s2/SAME LayerNorm-SiLU
    miniblock exactly (callers fall back to plain XLA otherwise)."""
    return (
        use_pallas("cnn")
        and tuple(kernel_shape[:2]) == (4, 4)
        and tuple(stride) == (2, 2)
        and padding == "SAME"
        and has_norm
        and act == "silu"
    )


def _silu(z):
    return z * jax.nn.sigmoid(z)


# =============================================================================
# encoder stage: conv k4/s2/SAME + LayerNorm + SiLU
# =============================================================================


def _enc_kernel(xp_ref, w_ref, scale_ref, offset_ref, y_ref, *, eps, ho, wo,
                residuals=False, hat_ref=None, rstd_ref=None):
    xp = xp_ref[:]  # [bn, H+2, W+2, Cin], pre-padded
    bn, cin = xp.shape[0], xp.shape[-1]
    cout = w_ref.shape[-1]
    # im2col via 16 strided parity slices: out pixel (i, j) reads padded rows
    # 2i+kh, cols 2j+kw — slice start kh, stride 2, length ho
    cols = [
        jax.lax.slice(
            xp,
            (0, kh, kw, 0),
            (bn, kh + 2 * ho - 1, kw + 2 * wo - 1, cin),
            (1, 2, 2, 1),
        )
        for kh in range(4)
        for kw in range(4)
    ]
    patches = jnp.concatenate(cols, axis=-1).reshape(bn * ho * wo, 16 * cin)
    pre = jnp.dot(patches, w_ref[:], preferred_element_type=jnp.float32)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    centered = pre - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    hat = centered * rstd
    z = hat * scale_ref[:] + offset_ref[:]
    y = _silu(z)
    y_ref[:] = y.reshape(bn, ho, wo, cout).astype(y_ref.dtype)
    if residuals:
        hat_ref[:] = hat.reshape(bn, ho, wo, cout)
        rstd_ref[:] = rstd.reshape(bn, ho, wo, 1)


def _enc_call(x, wmat, scale, offset, eps, residuals):
    n, h, w, cin = x.shape
    ho, wo = h // 2, w // 2
    cout = wmat.shape[-1]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    bn = max(1, min(n, _ROWS_TARGET // max(ho * wo, 1)))
    out_shape = [jax.ShapeDtypeStruct((n, ho, wo, cout), x.dtype)]
    out_specs = [
        pl.BlockSpec((bn, ho, wo, cout), lambda i: (i, 0, 0, 0), memory_space=_VMEM)
    ]
    if residuals:
        out_shape += [
            jax.ShapeDtypeStruct((n, ho, wo, cout), jnp.float32),
            jax.ShapeDtypeStruct((n, ho, wo, 1), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (bn, ho, wo, cout), lambda i: (i, 0, 0, 0), memory_space=_VMEM
            ),
            pl.BlockSpec((bn, ho, wo, 1), lambda i: (i, 0, 0, 0), memory_space=_VMEM),
        ]
    kernel = functools.partial(
        _enc_kernel, eps=eps, ho=ho, wo=wo, residuals=residuals
    )
    if residuals:
        body = lambda xr, wr, sr, or_, yr, hr, rr: kernel(
            xr, wr, sr, or_, yr, hat_ref=hr, rstd_ref=rr
        )
    else:
        body = kernel
    out = pl.pallas_call(
        body,
        grid=(_cdiv(n, bn),),
        out_shape=tuple(out_shape) if residuals else out_shape[0],
        in_specs=[
            pl.BlockSpec(
                (bn, h + 2, w + 2, cin), lambda i: (i, 0, 0, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(wmat.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=tuple(out_specs) if residuals else out_specs[0],
        interpret=_interpret_mode(),
    )(xp, wmat, scale, offset)
    return out


def _enc_conv(x, w):
    """The bare conv (XLA) — its VJP supplies dx/dW in the backward."""
    return jax.lax.conv_general_dilated(
        x,
        w.astype(x.dtype),
        window_strides=(2, 2),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _ln_silu_bwd(dy, hat, rstd, scale, offset):
    """Grad of SiLU(LayerNorm(pre)) wrt pre / scale / offset from the saved
    normalized activations and inverse stddev."""
    dy = dy.astype(jnp.float32)
    z = hat * scale + offset
    sig = jax.nn.sigmoid(z)
    dz = dy * (sig * (1.0 + z * (1.0 - sig)))  # SiLU'
    dscale = jnp.sum(dz * hat, axis=tuple(range(dz.ndim - 1)))
    doffset = jnp.sum(dz, axis=tuple(range(dz.ndim - 1)))
    g = dz * scale
    dpre = rstd * (
        g
        - jnp.mean(g, axis=-1, keepdims=True)
        - hat * jnp.mean(g * hat, axis=-1, keepdims=True)
    )
    return dpre, dscale, doffset


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def conv_ln_silu(x, w, scale, offset, eps=1e-3):
    """Fused Dreamer encoder stage. x: [N, H, W, Cin] (H, W even),
    w: [4, 4, Cin, Cout] conv kernel, scale/offset: LayerNorm affine."""
    cin, cout = w.shape[2], w.shape[3]
    return _enc_call(x, w.reshape(16 * cin, cout), scale, offset, eps, False)


def _conv_ln_silu_fwd(x, w, scale, offset, eps):
    cin, cout = w.shape[2], w.shape[3]
    y, hat, rstd = _enc_call(x, w.reshape(16 * cin, cout), scale, offset, eps, True)
    return y, (x, w, scale, offset, hat, rstd)


def _conv_ln_silu_bwd(eps, res, dy):
    x, w, scale, offset, hat, rstd = res
    dpre, dscale, doffset = _ln_silu_bwd(dy, hat, rstd, scale, offset)
    _, conv_vjp = jax.vjp(_enc_conv, x, w)
    dx, dw = conv_vjp(dpre.astype(x.dtype))
    return dx, dw.astype(w.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


conv_ln_silu.defvjp(_conv_ln_silu_fwd, _conv_ln_silu_bwd)


# =============================================================================
# decoder stage: subpixel deconv k4/s2/SAME + LayerNorm + SiLU
# =============================================================================


def _dec_kernel(xp_ref, w_ref, scale_ref, offset_ref, y_ref, *, eps, h, w,
                residuals=False, hat_ref=None, rstd_ref=None):
    xp = xp_ref[:]  # [bn, h+2, w+2, Cin], pre-padded
    bn, cin = xp.shape[0], xp.shape[-1]
    cout4 = w_ref.shape[-1]
    cout = cout4 // 4
    # dense 2x2 conv over the padded grid -> per-pixel 2x2 output phases
    cols = [
        jax.lax.slice(xp, (0, a, b, 0), (bn, a + h + 1, b + w + 1, cin))
        for a in range(2)
        for b in range(2)
    ]
    patches = jnp.concatenate(cols, axis=-1).reshape(bn * (h + 1) * (w + 1), 4 * cin)
    ph = jnp.dot(patches, w_ref[:], preferred_element_type=jnp.float32)
    ph = ph.reshape(bn, h + 1, w + 1, 2, 2, cout)
    # subpixel interleave (same phase selection as ConvTranspose2d._subpixel_k4s2)
    row0 = jnp.stack([ph[:, :h, :w, 0, 0], ph[:, :h, 1:, 0, 1]], axis=3)
    row1 = jnp.stack([ph[:, 1:, :w, 1, 0], ph[:, 1:, 1:, 1, 1]], axis=3)
    pre = jnp.stack([row0, row1], axis=2).reshape(bn * 2 * h * 2 * w, cout)
    mean = jnp.mean(pre, axis=-1, keepdims=True)
    centered = pre - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    hat = centered * rstd
    z = hat * scale_ref[:] + offset_ref[:]
    y = _silu(z)
    y_ref[:] = y.reshape(bn, 2 * h, 2 * w, cout).astype(y_ref.dtype)
    if residuals:
        hat_ref[:] = hat.reshape(bn, 2 * h, 2 * w, cout)
        rstd_ref[:] = rstd.reshape(bn, 2 * h, 2 * w, 1)


def _dec_call(x, wmat, scale, offset, eps, residuals):
    n, h, w, cin = x.shape
    cout = wmat.shape[-1] // 4
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    bn = max(1, min(n, _ROWS_TARGET // max(4 * h * w, 1)))
    out_shape = [jax.ShapeDtypeStruct((n, 2 * h, 2 * w, cout), x.dtype)]
    out_specs = [
        pl.BlockSpec(
            (bn, 2 * h, 2 * w, cout), lambda i: (i, 0, 0, 0), memory_space=_VMEM
        )
    ]
    if residuals:
        out_shape += [
            jax.ShapeDtypeStruct((n, 2 * h, 2 * w, cout), jnp.float32),
            jax.ShapeDtypeStruct((n, 2 * h, 2 * w, 1), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec(
                (bn, 2 * h, 2 * w, cout), lambda i: (i, 0, 0, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (bn, 2 * h, 2 * w, 1), lambda i: (i, 0, 0, 0), memory_space=_VMEM
            ),
        ]
    kernel = functools.partial(_dec_kernel, eps=eps, h=h, w=w, residuals=residuals)
    if residuals:
        body = lambda xr, wr, sr, or_, yr, hr, rr: kernel(
            xr, wr, sr, or_, yr, hat_ref=hr, rstd_ref=rr
        )
    else:
        body = kernel
    return pl.pallas_call(
        body,
        grid=(_cdiv(n, bn),),
        out_shape=tuple(out_shape) if residuals else out_shape[0],
        in_specs=[
            pl.BlockSpec(
                (bn, h + 2, w + 2, cin), lambda i: (i, 0, 0, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(wmat.shape, lambda i: (0, 0), memory_space=_VMEM),
            pl.BlockSpec(scale.shape, lambda i: (0,), memory_space=_VMEM),
            pl.BlockSpec(offset.shape, lambda i: (0,), memory_space=_VMEM),
        ],
        out_specs=tuple(out_specs) if residuals else out_specs[0],
        interpret=_interpret_mode(),
    )(xp, wmat, scale, offset)


def _dec_wmat(k):
    """[4, 4, Cin, Cout] transposed-conv kernel -> [4*Cin, 4*Cout] dense 2x2
    phase matrix, ordering matched to _dec_kernel's cols/phases (identical to
    ConvTranspose2d._subpixel_k4s2's regrouping)."""
    cin, cout = k.shape[2], k.shape[3]
    kk = k.reshape(2, 2, 2, 2, cin, cout)  # [a, dh, b, dw, cin, cout]
    return kk.transpose(0, 2, 4, 1, 3, 5).reshape(4 * cin, 4 * cout)


def _dec_deconv(x, k):
    """The bare transposed conv (XLA subpixel formulation) — VJP source for
    the backward."""
    n, h, w, cin = x.shape
    cout = k.shape[3]
    kk = _dec_wmat(k.astype(x.dtype)).reshape(2, 2, cin, 4 * cout)
    ph = jax.lax.conv_general_dilated(
        x, kk, window_strides=(1, 1), padding=((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ).reshape(n, h + 1, w + 1, 2, 2, cout)
    row0 = jnp.stack([ph[:, :h, :w, 0, 0], ph[:, :h, 1:, 0, 1]], axis=3)
    row1 = jnp.stack([ph[:, 1:, :w, 1, 0], ph[:, 1:, 1:, 1, 1]], axis=3)
    return jnp.stack([row0, row1], axis=2).reshape(n, 2 * h, 2 * w, cout)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def deconv_ln_silu(x, k, scale, offset, eps=1e-3):
    """Fused Dreamer decoder stage. x: [N, H, W, Cin],
    k: [4, 4, Cin, Cout] transposed-conv kernel, scale/offset: LN affine."""
    return _dec_call(x, _dec_wmat(k), scale, offset, eps, False)


def _deconv_ln_silu_fwd(x, k, scale, offset, eps):
    y, hat, rstd = _dec_call(x, _dec_wmat(k), scale, offset, eps, True)
    return y, (x, k, scale, offset, hat, rstd)


def _deconv_ln_silu_bwd(eps, res, dy):
    x, k, scale, offset, hat, rstd = res
    dpre, dscale, doffset = _ln_silu_bwd(dy, hat, rstd, scale, offset)
    _, vjp = jax.vjp(_dec_deconv, x, k)
    dx, dk = vjp(dpre.astype(x.dtype))
    return dx, dk.astype(k.dtype), dscale.astype(scale.dtype), doffset.astype(offset.dtype)


deconv_ln_silu.defvjp(_deconv_ln_silu_fwd, _deconv_ln_silu_bwd)
