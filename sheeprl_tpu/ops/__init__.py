from .math import (
    gae,
    lambda_values,
    lambda_values_dv2,
    lambda_values_dv3,
    normalize,
    polynomial_decay,
    symexp,
    symlog,
    two_hot,
)
from .moments import Moments
from .scan import scan_unroll
from . import distributions

__all__ = [
    "gae",
    "lambda_values",
    "lambda_values_dv2",
    "lambda_values_dv3",
    "normalize",
    "polynomial_decay",
    "symexp",
    "symlog",
    "two_hot",
    "Moments",
    "scan_unroll",
    "distributions",
]
