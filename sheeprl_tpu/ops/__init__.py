from .math import (
    gae,
    lambda_values,
    lambda_values_dv2,
    lambda_values_dv3,
    normalize,
    polynomial_decay,
    symexp,
    symlog,
    two_hot,
)
from .moments import Moments
from .scan import autotune_unroll, checkpoint_body, scan_unroll, set_unroll, unroll_mode
from . import distributions
from . import precision
from . import scan

__all__ = [
    "gae",
    "lambda_values",
    "lambda_values_dv2",
    "lambda_values_dv3",
    "normalize",
    "polynomial_decay",
    "symexp",
    "symlog",
    "two_hot",
    "Moments",
    "autotune_unroll",
    "checkpoint_body",
    "scan_unroll",
    "set_unroll",
    "unroll_mode",
    "distributions",
    "precision",
    "scan",
]
