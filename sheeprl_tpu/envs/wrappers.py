"""Environment wrappers.

Capability parity with /root/reference/sheeprl/envs/wrappers.py, re-designed
for the TPU pipeline's channel-LAST convention: images are `[H, W, C]`
everywhere (the NHWC layout TPU convs consume natively), and `FrameStack`
concatenates along the channel axis -> `[H, W, C * num_stack]`, so stacked
pixels feed `Conv2d` with zero reshapes on device.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Sequence

import gymnasium as gym
import numpy as np

__all__ = [
    "MaskVelocityWrapper",
    "ActionRepeat",
    "RestartOnException",
    "FrameStack",
    "DictObservation",
]


class StepLatencyWrapper(gym.Wrapper):
    """Model a real-time environment: every `step()` pays a fixed wall-clock
    latency without consuming host CPU (`time.sleep` releases the GIL and
    the core). Robots, remote/throttled simulators and rate-limited web
    envs all look like this to the learner — the env-step window is IDLE
    host time that background work (warm-start compilation, prefetchers)
    can genuinely hide, even on a single-core host.

    Enabled repo-wide by `SHEEPRL_TPU_ENV_LATENCY_MS` (see utils/env.py);
    `bench.py --algo warm_compile` uses it to put collection in the
    latency-bound regime its headline models."""

    def __init__(self, env: gym.Env, latency_ms: float):
        super().__init__(env)
        self._latency_s = float(latency_ms) / 1000.0

    def step(self, action):
        import time

        time.sleep(self._latency_s)
        return self.env.step(action)


def maybe_step_latency(env: gym.Env) -> gym.Env:
    """Apply StepLatencyWrapper when SHEEPRL_TPU_ENV_LATENCY_MS is set (>0)."""
    import os

    ms = os.environ.get("SHEEPRL_TPU_ENV_LATENCY_MS")
    try:
        ms_f = float(ms) if ms else 0.0
    except ValueError:
        ms_f = 0.0
    return StepLatencyWrapper(env, ms_f) if ms_f > 0 else env


class MaskVelocityWrapper(gym.ObservationWrapper):
    """Zero out velocity entries to make classic-control tasks partially
    observable (/root/reference/sheeprl/envs/wrappers.py:11-43)."""

    velocity_indices: dict[str, np.ndarray] = {
        "CartPole-v0": np.array([1, 3]),
        "CartPole-v1": np.array([1, 3]),
        "MountainCar-v0": np.array([1]),
        "MountainCarContinuous-v0": np.array([1]),
        "Pendulum-v1": np.array([2]),
        "LunarLander-v2": np.array([2, 3, 5]),
        "LunarLanderContinuous-v2": np.array([2, 3, 5]),
    }

    def __init__(self, env: gym.Env):
        super().__init__(env)
        assert env.unwrapped.spec is not None
        env_id = env.unwrapped.spec.id
        self.mask = np.ones_like(env.observation_space.sample())
        try:
            self.mask[self.velocity_indices[env_id]] = 0.0
        except KeyError as e:
            raise NotImplementedError(f"velocity masking not implemented for {env_id}") from e

    def observation(self, observation: np.ndarray) -> np.ndarray:
        return observation * self.mask


class ActionRepeat(gym.Wrapper):
    """Repeat the action `amount` times, accumulating reward and stopping at
    episode end (/root/reference/sheeprl/envs/wrappers.py:46-70)."""

    def __init__(self, env: gym.Env, amount: int = 1):
        super().__init__(env)
        if amount <= 0:
            raise ValueError("`amount` must be a positive integer")
        self._amount = amount

    @property
    def action_repeat(self) -> int:
        return self._amount

    def step(self, action):
        total_reward, terminated, truncated = 0.0, False, False
        obs, info = None, {}
        for _ in range(self._amount):
            obs, reward, terminated, truncated, info = self.env.step(action)
            total_reward += float(reward)
            if terminated or truncated:
                break
        return obs, total_reward, terminated, truncated, info


class RestartOnException(gym.Wrapper):
    """Recreate a crashed env (flaky Minecraft-style backends), capped at
    `maxfails` per `window` seconds; flags `info["restart_on_exception"]` so
    the training loop can patch its buffer
    (/root/reference/sheeprl/envs/wrappers.py:73-122).

    ISSUE 12: shares the generic `resilience.envwrap` machinery's
    observability — restarts count into `Fault/env_restarts`, emit
    `fault.env_error`/`fault.recovered` telemetry events, and the
    deterministic `env.step@n` injection site fires inside the retry scope
    here too (the dreamer mains wrap this OUTSIDE the per-thunk
    `RestartingEnv`, so whichever wrapper sees the fault first recovers it).
    Semantics differ from `RestartingEnv` on purpose: this wrapper returns a
    NON-terminal transition plus the info flag, and the dreamer loops patch
    the replay ring themselves (dreamer_v3.py buffer surgery)."""

    def __init__(
        self,
        env_fn: Callable[[], gym.Env],
        exceptions: Sequence[type] = (Exception,),
        window: float = 300.0,
        maxfails: int = 2,
        wait: float = 20.0,
    ):
        if not isinstance(exceptions, (tuple, list)):
            exceptions = (exceptions,)
        self._env_fn = env_fn
        self._exceptions = tuple(exceptions)
        self._window = window
        self._maxfails = maxfails
        self._wait = wait
        self._last = time.time()
        self._fails = 0
        super().__init__(env_fn())

    def _record_failure(self, err: Exception, where: str) -> None:
        from ..resilience import inject

        now = time.time()
        if now > self._last + self._window:
            self._last = now
            self._fails = 1
        else:
            self._fails += 1
        inject.count("Fault/env_errors")
        from ..telemetry import emit

        emit(
            "fault.env_error",
            error=f"{type(err).__name__}: {err}"[:300],
            attempt=self._fails,
            limit=self._maxfails,
            where=where,
        )
        if self._fails > self._maxfails:
            raise RuntimeError(f"env crashed too many times: {self._fails}") from err
        gym.logger.warn(
            f"{where} - restarting env after crash with {type(err).__name__}: {err}"
        )
        time.sleep(self._wait)

    def step(self, action):
        from ..resilience import inject

        try:
            # inject only when no inner RestartingEnv already owns the site
            # (double-wrapped dreamer envs would advance the counter twice)
            if not getattr(self.env, "_sheeprl_resilient", False):
                spec = inject.get_plan().fire_next("env.step")
                if spec is not None:
                    raise inject.InjectedFault(
                        f"injected env.step fault: {spec.describe()}"
                    )
            return self.env.step(action)
        except self._exceptions as e:
            self._record_failure(e, "STEP")
            self.env = self._env_fn()
            obs, info = self.env.reset()
            inject.note_recovery("env.step", "env_restarts", attempt=self._fails)
            info["restart_on_exception"] = True
            return obs, 0.0, False, False, info

    def reset(self, *, seed=None, options=None):
        try:
            return self.env.reset(seed=seed, options=options)
        except self._exceptions as e:
            self._record_failure(e, "RESET")
            self.env = self._env_fn()
            obs, info = self.env.reset()
            info["restart_on_exception"] = True
            return obs, info


class FrameStack(gym.Wrapper):
    """Stack the last `num_stack` frames of each image key along the CHANNEL
    axis (`[H, W, C] -> [H, W, C * num_stack]`), optionally dilated.

    Same capability as the reference FrameStack
    (/root/reference/sheeprl/envs/wrappers.py:125-182) but channel-last and
    channel-concatenated: the output feeds NHWC convs directly instead of
    introducing a stack axis that must be folded on device.
    """

    def __init__(self, env: gym.Env, num_stack: int, cnn_keys: Sequence[str], dilation: int = 1):
        super().__init__(env)
        if num_stack <= 0:
            raise ValueError(f"num_stack must be > 0, got {num_stack}")
        if dilation <= 0:
            raise ValueError(f"dilation must be > 0, got {dilation}")
        if not isinstance(env.observation_space, gym.spaces.Dict):
            raise RuntimeError(
                f"expected a Dict observation space, got {type(env.observation_space)}"
            )
        self._num_stack = num_stack
        self._dilation = dilation
        self._cnn_keys = [
            k
            for k, v in env.observation_space.spaces.items()
            if k in cnn_keys and len(v.shape) == 3
        ]
        if not self._cnn_keys:
            raise RuntimeError("specify at least one valid cnn key to stack")
        spaces = dict(env.observation_space.spaces)
        for k in self._cnn_keys:
            sp = env.observation_space[k]
            h, w, c = sp.shape
            spaces[k] = gym.spaces.Box(
                np.concatenate([sp.low] * num_stack, axis=-1),
                np.concatenate([sp.high] * num_stack, axis=-1),
                (h, w, c * num_stack),
                sp.dtype,
            )
        self.observation_space = gym.spaces.Dict(spaces)
        self._frames = {k: deque(maxlen=num_stack * dilation) for k in self._cnn_keys}

    def _stacked(self, key: str) -> np.ndarray:
        frames = list(self._frames[key])[self._dilation - 1 :: self._dilation]
        assert len(frames) == self._num_stack
        return np.concatenate(frames, axis=-1)

    def step(self, action):
        obs, reward, terminated, truncated, info = self.env.step(action)
        for k in self._cnn_keys:
            self._frames[k].append(obs[k])
            obs[k] = self._stacked(k)
        return obs, reward, terminated, truncated, info

    def reset(self, *, seed=None, options=None):
        obs, info = self.env.reset(seed=seed, options=options)
        for k in self._cnn_keys:
            self._frames[k].clear()
            for _ in range(self._num_stack * self._dilation):
                self._frames[k].append(obs[k])
            obs[k] = self._stacked(k)
        return obs, info


class DictObservation(gym.ObservationWrapper):
    """Wrap a Box observation into a single-key dict observation (the
    reference does this inline with TransformObservation,
    /root/reference/sheeprl/utils/env.py:185-220)."""

    def __init__(self, env: gym.Env, key: str):
        super().__init__(env)
        self._key = key
        self.observation_space = gym.spaces.Dict({key: env.observation_space})

    def observation(self, observation):
        return {self._key: observation}
