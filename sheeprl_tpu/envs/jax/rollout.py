"""Anakin rollout collectors — the fully-jitted `scan(policy ∘ env.step)`.

Podracer's Anakin arrangement (arXiv:2104.06272): with the environment
expressed as pure JAX (`core.VecJaxEnv`), a whole rollout becomes ONE
jitted call — `lax.scan` over `policy_step ∘ env.step` — that runs
entirely on device. No action pull, no observation put, no per-step
dispatch: the host's only involvement is launching the scan and, once per
rollout, reading the episode-statistics scalars. That makes per-step host
cost structurally zero (PRs 4–5 merely *hid* it behind async transfers)
and is what moves collection into the millions-of-env-steps/sec regime.

Two collector factories share the scan skeleton:

- `make_ppo_collector`: rows match the PPO rollout store exactly
  (`obs_keys..., actions (one-hot/raw), logprobs, values, rewards,
  dones=done-entering-the-step`) so the trajectory feeds the existing GAE +
  train jits unchanged;
- `make_dreamer_collector`: rows match the DreamerV3 replay layout
  (`obs_keys..., actions, rewards, dones, is_first`, host-shifted
  alignment: reward/done of step t-1 ride row t) and scatter straight into
  the device replay ring via `AsyncReplayBuffer.reserve`/`add_direct` —
  the ONLY difference vs the host layout is that episode boundaries are
  one row (the auto-reset row carries the terminal reward/done next to
  `is_first=1`) instead of the host path's separate terminal row.

Both return, besides the trajectory, an `ep` dict of on-device scalars
(`episodes`, `return_sum`, `length_sum`) — one `device_get` per rollout
replaces the host path's per-step info parsing.

The scan body is a hot loop in the sheeplint sense — the
`# sheeplint: hotloop` markers arm SL007 so any future `.item()`/
`np.asarray` slipped into the body fails CI, and
`tests/test_envs/test_jax_envs.py` runs a compiled collector under
`jax.transfer_guard("disallow")` as the runtime half of that guarantee.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ... import nn
from .core import VecJaxEnv

__all__ = [
    "PPOCollectorCarry",
    "DreamerCollectorCarry",
    "make_ppo_collector",
    "make_dreamer_collector",
    "random_action_sampler",
]


class PPOCollectorCarry(nn.Module):
    """Everything the PPO rollout scan threads between steps (and between
    rollouts — the carry survives across updates, exactly like the host
    loop's `obs`/`next_done`)."""

    vec: Any  # VecEnvState
    obs: Any  # dict of [N, ...] observations
    prev_done: jax.Array  # [N, 1] f32: done flag entering the next step


class DreamerCollectorCarry(nn.Module):
    vec: Any  # VecEnvState
    obs: Any  # dict of [N, ...] observations (raw; uint8 pixels)
    prev_reward: jax.Array  # [N, 1] f32 (host-shifted row alignment)
    prev_done: jax.Array  # [N, 1] f32
    is_first: jax.Array  # [N, 1] f32


def _episode_summary(done_f, ep_return, ep_length):
    """Reduce per-step done/episode-stat stacks to three scalars — the one
    device->host pull reward logging costs per rollout."""
    return {
        "episodes": jnp.sum(done_f),
        "return_sum": jnp.sum(ep_return * done_f),
        "length_sum": jnp.sum(ep_length * done_f),
    }


def _env_native_actions(
    actions: jax.Array, actions_dim: Sequence[int], is_continuous: bool
):
    """Jit-side twin of `ppo.agent.one_hot_to_env_actions`: the env-native
    action layout (`int32 [N]` argmax for a single discrete head, `[N, H]`
    for multi-discrete, raw floats for continuous)."""
    if is_continuous:
        return actions
    out, start = [], 0
    for dim in actions_dim:
        out.append(jnp.argmax(actions[..., start : start + dim], axis=-1))
        start += dim
    idx = jnp.stack(out, axis=-1).astype(jnp.int32)
    if len(actions_dim) == 1:
        return idx[..., 0]
    return idx


def random_action_sampler(
    action_space, actions_dim: Sequence[int], is_continuous: bool
) -> Callable:
    """Device-side analogue of the hosts' `action_space.sample()` warmup:
    `sample(key, n) -> actions [n, sum(actions_dim)]` (one-hot for discrete
    heads, uniform-in-box for continuous). Bounds are baked as constants
    from the gym space so the sampler stays pure."""
    if is_continuous:
        low = jnp.asarray(action_space.low, jnp.float32)
        high = jnp.asarray(action_space.high, jnp.float32)

        def sample(key, n):
            u = jax.random.uniform(key, (n,) + low.shape, jnp.float32)
            return (low + u * (high - low)).reshape(n, -1)

        return sample

    dims = tuple(int(d) for d in actions_dim)

    def sample(key, n):
        keys = jax.random.split(key, len(dims))
        hots = [
            jax.nn.one_hot(
                jax.random.randint(k, (n,), 0, d), d, dtype=jnp.float32
            )
            for k, d in zip(keys, dims)
        ]
        return jnp.concatenate(hots, axis=-1)

    return sample


def make_ppo_collector(
    venv: VecJaxEnv,
    rollout_steps: int,
    actions_dim: Sequence[int],
    is_continuous: bool,
) -> Callable:
    """Build `collect(agent, carry, key) -> (carry', traj, ep)` where
    `traj` is the `[T, N, ...]` rollout-store layout PPO's GAE + train jits
    already consume. Jit (or `CompilePlan.register`) the result — one call
    is one whole rollout."""

    def collect(agent, carry: PPOCollectorCarry, key):
        def body(c, _):  # sheeplint: hotloop
            vec, obs, prev_done, k = c
            k, k_act, k_step = jax.random.split(k, 3)
            actions, logprob, _, value = agent(obs, key=k_act)
            env_actions = _env_native_actions(actions, actions_dim, is_continuous)
            vec, next_obs, reward, done, info = venv.step(vec, env_actions, k_step)
            row = dict(obs)
            row.update(
                actions=actions,
                logprobs=logprob,
                values=value,
                rewards=reward[:, None],
                dones=prev_done,
            )
            done_f = done.astype(jnp.float32)
            stats = (done_f, info["ep_return"], info["ep_length"].astype(jnp.float32))
            return (vec, next_obs, done_f[:, None], k), (row, stats)

        (vec, obs, prev_done, _), (traj, stats) = jax.lax.scan(
            body,
            (carry.vec, carry.obs, carry.prev_done, key),
            None,
            length=rollout_steps,
        )
        ep = _episode_summary(*stats)
        return PPOCollectorCarry(vec=vec, obs=obs, prev_done=prev_done), traj, ep

    return collect


def make_dreamer_collector(
    venv: VecJaxEnv,
    steps: int,
    actions_dim: Sequence[int],
    is_continuous: bool,
    dev_preprocess: Callable,
    clip_rewards: bool = False,
    random_actions: bool = False,
) -> Callable:
    """Build `collect(player, player_state, carry, key, expl) ->
    (player_state', carry', traj, ep)` producing `steps` device replay rows
    `[T, N, ...]` in the DreamerV3 ring layout, ready for
    `rb.reserve(steps)` + `rb.add_direct`. With `random_actions=True` the
    player is threaded through untouched and actions come from the device
    `random_action_sampler` — the learning-starts warmup without leaving
    the chip."""
    sampler = random_action_sampler(
        venv.single_action_space, actions_dim, is_continuous
    )

    def collect(player, player_state, carry: DreamerCollectorCarry, key, expl):
        def body(c, _):  # sheeplint: hotloop
            pstate, vec, obs, prev_reward, prev_done, is_first, k = c
            k, k_act, k_step = jax.random.split(k, 3)
            if random_actions:
                actions = sampler(k_act, venv.num_envs)
            else:
                pstate, actions = player.step(
                    pstate, dev_preprocess(obs), k_act, expl, is_training=True
                )
            row = dict(obs)
            row.update(
                actions=actions.astype(jnp.float32),
                rewards=prev_reward,
                dones=prev_done,
                is_first=is_first,
            )
            env_actions = _env_native_actions(
                actions.astype(jnp.float32), actions_dim, is_continuous
            )
            vec, next_obs, reward, done, info = venv.step(vec, env_actions, k_step)
            if clip_rewards:
                reward = jnp.tanh(reward)
            done_f = done.astype(jnp.float32)[:, None]
            if not random_actions:
                pstate = player.reset_states(pstate, done_f[:, 0])
            stats = (
                done_f[:, 0],
                info["ep_return"],
                info["ep_length"].astype(jnp.float32),
            )
            # next row's host-shifted fields: this step's reward/done land on
            # the auto-reset row together with its is_first flag (the host
            # path splits them onto a separate terminal row instead)
            return (pstate, vec, next_obs, reward[:, None], done_f, done_f, k), (
                row,
                stats,
            )

        (pstate, vec, obs, prev_reward, prev_done, is_first, _), (traj, stats) = (
            jax.lax.scan(
                body,
                (
                    player_state,
                    carry.vec,
                    carry.obs,
                    carry.prev_reward,
                    carry.prev_done,
                    carry.is_first,
                    key,
                ),
                None,
                length=steps,
            )
        )
        ep = _episode_summary(*stats)
        new_carry = DreamerCollectorCarry(
            vec=vec,
            obs=obs,
            prev_reward=prev_reward,
            prev_done=prev_done,
            is_first=is_first,
        )
        return pstate, new_carry, traj, ep

    return collect
