"""On-device environment API — the Anakin arrangement's env half.

Podracer (arXiv:2104.06272) co-locates environment and agent on the same
chip so a `lax.scan` over `policy -> env.step` runs with zero host
round-trips per step. Everything in this package exists to make that scan
legal JAX: an environment is a *pure function pair* over an explicit pytree
state —

    env.reset(key)                 -> (EnvState, obs_dict)
    env.step(state, action, key)   -> (EnvState, obs_dict, reward, term, trunc)

with all configuration (physics constants, episode limits, image sizes) as
static metadata on an `nn.Module` subclass, so the env itself has no array
leaves and traces for free. Observations are dicts keyed exactly like the
host pipeline (`utils/env.py`): vector obs under ``"state"``, pixels under
``"rgb"`` as uint8 NHWC — the same agent/encoder code runs on either
backend.

`VecJaxEnv` lifts a single env to a fixed batch of `num_envs` parallel
copies via `jax.vmap`, with **same-step auto-reset** matching the host
vector runners (`envs/vector.py`): when an env finishes, the returned
observation is already the reset one and the final pre-reset observation is
surfaced in the step info — the policy never sees a stale terminal obs, and
the batch shape never changes, so thousands of envs run as one fused XLA
program. Episode statistics (return/length) are part of the vector state so
reward logging needs no host-side bookkeeping in the hot loop.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ... import nn

__all__ = ["JaxEnv", "VecEnvState", "VecJaxEnv", "tree_select"]


def tree_select(mask: jax.Array, on_true: Any, on_false: Any) -> Any:
    """Per-env select between two identically-shaped pytrees: `mask` is
    `[N]` bool/float, broadcast against each leaf's trailing dims. The
    auto-reset primitive (done rows take the freshly-reset leaf)."""

    def one(a, b):
        m = mask.reshape(mask.shape + (1,) * (a.ndim - mask.ndim)).astype(bool)
        return jnp.where(m, a, b)

    return jax.tree_util.tree_map(one, on_true, on_false)


class JaxEnv(nn.Module):
    """Base class for pure-JAX environments. Subclasses define:

    - a registered pytree ``State`` (subclass `nn.Module`; array leaves
      only — auto-reset `tree_select`s whole states);
    - ``reset(key) -> (State, obs_dict)`` and
      ``step(state, action, key) -> (State, obs_dict, reward, terminated,
      truncated)``, both pure and single-env (batching is `VecJaxEnv`'s
      job); rewards/flags are scalars (`f32`, `bool`, `bool`);
    - host-side space descriptors: `observation_space` / `action_space`
      (gymnasium spaces, used for agent init and eval-time wrappers — never
      inside a jit).

    Actions arrive in the env-native layout the host twins use: an `int32`
    scalar for `Discrete`, `f32 [act_dim]` for `Box`.
    """

    # subclasses override via nn.static fields; declared here for tooling
    def reset(self, key):  # pragma: no cover - interface
        raise NotImplementedError

    def step(self, state, action, key):  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def observation_space(self):  # pragma: no cover - interface
        raise NotImplementedError

    @property
    def action_space(self):  # pragma: no cover - interface
        raise NotImplementedError


class VecEnvState(nn.Module):
    """State of a `VecJaxEnv`: the vmapped per-env states plus on-device
    episode statistics (so reward logging costs one pull per *rollout*, not
    one per step)."""

    env_state: Any
    ep_return: jax.Array  # [N] f32 running episode return
    ep_length: jax.Array  # [N] i32 running episode length


class VecJaxEnv(nn.Module):
    """`num_envs` parallel copies of a pure-JAX env with same-step
    auto-reset — the batched env the Anakin rollout scans over."""

    env: Any
    num_envs: int = nn.static(default=1)

    def reset(self, key) -> tuple[VecEnvState, dict]:
        keys = jax.random.split(key, self.num_envs)
        states, obs = jax.vmap(self.env.reset)(keys)
        return (
            VecEnvState(
                env_state=states,
                ep_return=jnp.zeros((self.num_envs,), jnp.float32),
                ep_length=jnp.zeros((self.num_envs,), jnp.int32),
            ),
            obs,
        )

    def step(
        self, state: VecEnvState, actions: jax.Array, key
    ) -> tuple[VecEnvState, dict, jax.Array, jax.Array, dict]:
        """One batched step with auto-reset. Returns
        `(state', obs, reward [N] f32, done [N] bool, info)` where `obs` is
        already the reset observation for finished envs and `info` carries
        `final_obs` (the true pre-reset observation), `terminated`,
        `truncated`, and the completed-episode `ep_return`/`ep_length`
        (valid where `done`)."""
        step_key, reset_key = jax.random.split(key)
        step_keys = jax.random.split(step_key, self.num_envs)
        states, obs, reward, term, trunc = jax.vmap(self.env.step)(
            state.env_state, actions, step_keys
        )
        done = jnp.logical_or(term, trunc)
        reset_keys = jax.random.split(reset_key, self.num_envs)
        fresh_states, fresh_obs = jax.vmap(self.env.reset)(reset_keys)
        ep_return = state.ep_return + reward
        ep_length = state.ep_length + 1
        info = {
            "final_obs": obs,
            "terminated": term,
            "truncated": trunc,
            "ep_return": ep_return,
            "ep_length": ep_length,
        }
        new_state = VecEnvState(
            env_state=tree_select(done, fresh_states, states),
            ep_return=jnp.where(done, 0.0, ep_return),
            ep_length=jnp.where(done, 0, ep_length),
        )
        return new_state, tree_select(done, fresh_obs, obs), reward, done, info

    # -- host-side conveniences (never traced) -------------------------------
    @property
    def single_observation_space(self):
        return self.env.observation_space

    @property
    def single_action_space(self):
        return self.env.action_space
