"""PixelToy — a pure-JAX pixel-observation toy env for the Anakin path.

A grid-world chase rendered ON DEVICE as uint8 NHWC frames: the agent (red
block) must reach the goal (green block) on a `grid x grid` board drawn
into a `size x size x 3` image (`"rgb"`, uint8 — the exact layout the host
pixel pipeline emits, so the CNN encoders run unchanged). Five discrete
actions (noop/up/down/left/right), +1 terminal reward at the goal, a small
per-step penalty, truncation at `max_episode_steps`. Rendering is pure
broadcasting arithmetic — no host round-trip anywhere — which makes this
the pixel-rate stress test for the jitted collector: thousands of envs
render thousands of frames per `lax.scan` step inside one XLA program.

A host twin for eval/debugging exists via `gym_compat.JaxEnvGymWrapper`
(`make_dict_env` dispatches the `pixeltoy` env id to it)."""

from __future__ import annotations

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from ... import nn
from .core import JaxEnv

__all__ = ["PixelToyState", "JaxPixelToy"]

# action -> (drow, dcol): noop, up, down, left, right
_MOVES = np.array([[0, 0], [-1, 0], [1, 0], [0, -1], [0, 1]], dtype=np.int32)


class PixelToyState(nn.Module):
    agent: jax.Array  # [2] i32 (row, col) in grid cells
    goal: jax.Array  # [2] i32 (row, col) in grid cells
    t: jax.Array  # [] i32 steps since reset


class JaxPixelToy(JaxEnv):
    size: int = nn.static(default=64)  # rendered image side (pixels)
    grid: int = nn.static(default=16)  # board side (cells)
    max_episode_steps: int = nn.static(default=128)
    step_penalty: float = nn.static(default=0.01)

    def _spawn(self, key):
        """Agent and goal on distinct cells: the goal re-rolls one
        deterministic offset when it collides with the agent."""
        k_agent, k_goal = jax.random.split(key)
        agent = jax.random.randint(k_agent, (2,), 0, self.grid, jnp.int32)
        goal = jax.random.randint(k_goal, (2,), 0, self.grid, jnp.int32)
        collide = jnp.all(goal == agent)
        goal = jnp.where(collide, (goal + 1) % self.grid, goal)
        return agent, goal

    def reset(self, key):
        agent, goal = self._spawn(key)
        state = PixelToyState(agent=agent, goal=goal, t=jnp.zeros((), jnp.int32))
        return state, {"rgb": self._render(state)}

    def _render(self, state: PixelToyState) -> jax.Array:
        cell = self.size // self.grid
        px = jnp.arange(self.size) // cell  # pixel row/col -> board cell
        agent = (px[:, None] == state.agent[0]) & (px[None, :] == state.agent[1])
        goal = (px[:, None] == state.goal[0]) & (px[None, :] == state.goal[1])
        zeros = jnp.zeros((self.size, self.size), bool)
        return (
            jnp.stack([agent, goal, zeros], axis=-1).astype(jnp.uint8) * 255
        )

    def step(self, state: PixelToyState, action, key):
        del key  # deterministic dynamics; key kept for the uniform env API
        move = jnp.asarray(_MOVES)[action]
        agent = jnp.clip(state.agent + move, 0, self.grid - 1)
        reached = jnp.all(agent == state.goal)
        t = state.t + 1
        new = PixelToyState(agent=agent, goal=state.goal, t=t)
        reward = jnp.where(reached, 1.0, -self.step_penalty).astype(jnp.float32)
        return (
            new,
            {"rgb": self._render(new)},
            reward,
            reached,
            t >= self.max_episode_steps,
        )

    @property
    def observation_space(self):
        return gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(
                    0, 255, (self.size, self.size, 3), np.uint8
                )
            }
        )

    @property
    def action_space(self):
        return gym.spaces.Discrete(len(_MOVES))
