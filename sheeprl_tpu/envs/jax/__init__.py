"""Pure-JAX on-device environments + the Anakin rollout collectors.

See `core.py` for the env API, `rollout.py` for the jitted collectors, and
`howto/jax_envs.md` for authoring guidance and the `--env_backend` flag.
"""

from __future__ import annotations

from .cartpole import CartPoleState, JaxCartPole
from .core import JaxEnv, VecEnvState, VecJaxEnv, tree_select
from .gym_compat import JaxEnvGymWrapper
from .pendulum import JaxPendulum, PendulumState
from .pixeltoy import JaxPixelToy, PixelToyState
from .rollout import (
    DreamerCollectorCarry,
    PPOCollectorCarry,
    make_dreamer_collector,
    make_ppo_collector,
    random_action_sampler,
)

__all__ = [
    "CartPoleState",
    "DreamerCollectorCarry",
    "JaxCartPole",
    "JaxEnv",
    "JaxEnvGymWrapper",
    "JaxPendulum",
    "JaxPixelToy",
    "PPOCollectorCarry",
    "PendulumState",
    "PixelToyState",
    "VecEnvState",
    "VecJaxEnv",
    "has_jax_env",
    "make_jax_env",
    "make_ppo_collector",
    "make_dreamer_collector",
    "random_action_sampler",
    "tree_select",
]

# env-id registry: the ids the host pipeline already understands map to
# their on-device twins, plus the JAX-only pixel toy
_REGISTRY = {
    "cartpole-v1": JaxCartPole,
    "pendulum-v1": JaxPendulum,
    "pixeltoy": JaxPixelToy,
    "pixeltoy-v0": JaxPixelToy,
}


def has_jax_env(env_id: str) -> bool:
    """True when `env_id` has a pure-JAX implementation (`--env_backend
    jax` is available for it)."""
    return env_id.lower() in _REGISTRY


def make_jax_env(env_id: str, **overrides) -> JaxEnv:
    """Build the pure-JAX env registered under `env_id` (case-insensitive).
    `overrides` become static config fields (e.g. `max_episode_steps`)."""
    cls = _REGISTRY.get(env_id.lower())
    if cls is None:
        raise ValueError(
            f"no pure-JAX environment registered for {env_id!r}; available: "
            f"{sorted(_REGISTRY)} (use --env_backend host for everything else)"
        )
    return cls(**overrides)
