"""Host adapter: run a pure-JAX env as an ordinary gymnasium.Env.

The bridge that keeps both backends honest. It serves three jobs:

- **eval**: the algo mains' final greedy evaluation runs on a single host
  env; envs that exist only as JAX (``pixeltoy``) get their gym twin here
  (`utils/env.py` dispatches the env id to this wrapper);
- **parity tests**: `tests/test_envs/test_jax_envs.py` steps the wrapper
  against real Gymnasium envs;
- **host-backend runs**: `--env_backend host` with a JAX-only env id still
  works — the env steps one-at-a-time through the normal vector runners.

Single-env `step`/`reset` are jitted once per wrapper; dynamics are
therefore bit-identical to the on-device Anakin path."""

from __future__ import annotations

from typing import Any

import gymnasium as gym
import jax
import numpy as np

__all__ = ["JaxEnvGymWrapper"]


class JaxEnvGymWrapper(gym.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(self, env: Any, seed: int = 0):
        self._env = env
        self._step = jax.jit(env.step)
        self._reset = jax.jit(env.reset)
        self._state = None
        self._key = jax.random.PRNGKey(seed)
        self.observation_space = env.observation_space
        self.action_space = env.action_space
        self.render_mode = "rgb_array"

    def _split(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    @staticmethod
    def _host_obs(obs: dict) -> dict:
        return {k: np.asarray(v) for k, v in obs.items()}

    def reset(self, *, seed: int | None = None, options: dict | None = None):
        super().reset(seed=seed)
        if seed is not None:
            self._key = jax.random.PRNGKey(seed)
        self._state, obs = self._reset(self._split())
        return self._host_obs(obs), {}

    def step(self, action):
        if isinstance(self.action_space, gym.spaces.Discrete):
            action = np.int32(action)
        else:
            action = np.asarray(action, np.float32)
        self._state, obs, reward, term, trunc = self._step(
            self._state, action, self._split()
        )
        return (
            self._host_obs(obs),
            float(reward),
            bool(term),
            bool(trunc),
            {},
        )

    def render(self):
        if self._state is not None and hasattr(self._env, "_render"):
            return np.asarray(self._env._render(self._state))
        return None
