"""Pure-JAX Pendulum — dynamics parity with Gymnasium's `Pendulum-v1`.

Same torque-limited pendulum swing-up ODE, cost function and reset
distribution as `gymnasium/envs/classic_control/pendulum.py`; the 200-step
`TimeLimit` truncation of the registered v1 spec is folded into the state's
step counter. The env never terminates — episodes end by truncation only,
exactly like the host twin."""

from __future__ import annotations

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from ... import nn
from .core import JaxEnv

__all__ = ["PendulumState", "JaxPendulum"]

_MAX_SPEED = 8.0
_MAX_TORQUE = 2.0
_DT = 0.05
_G = 10.0
_M = 1.0
_L = 1.0
_RESET_X = np.pi  # DEFAULT_X: theta in [-pi, pi]
_RESET_Y = 1.0  # DEFAULT_Y: theta_dot in [-1, 1]


def _angle_normalize(x):
    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class PendulumState(nn.Module):
    state: jax.Array  # [2] f32: theta, theta_dot
    t: jax.Array  # [] i32 steps since reset (TimeLimit counter)


class JaxPendulum(JaxEnv):
    max_episode_steps: int = nn.static(default=200)

    def reset(self, key):
        high = jnp.asarray([_RESET_X, _RESET_Y], jnp.float32)
        state = jax.random.uniform(key, (2,), jnp.float32, -1.0, 1.0) * high
        return PendulumState(state=state, t=jnp.zeros((), jnp.int32)), {
            "state": self._obs(state)
        }

    @staticmethod
    def _obs(state):
        th, thdot = state[0], state[1]
        return jnp.stack([jnp.cos(th), jnp.sin(th), thdot]).astype(jnp.float32)

    def step(self, state: PendulumState, action, key):
        del key  # deterministic dynamics; key kept for the uniform env API
        th, thdot = state.state[0], state.state[1]
        u = jnp.clip(action.reshape(()), -_MAX_TORQUE, _MAX_TORQUE)
        costs = (
            _angle_normalize(th) ** 2 + 0.1 * thdot**2 + 0.001 * (u**2)
        )
        newthdot = thdot + (
            3 * _G / (2 * _L) * jnp.sin(th) + 3.0 / (_M * _L**2) * u
        ) * _DT
        newthdot = jnp.clip(newthdot, -_MAX_SPEED, _MAX_SPEED)
        newth = th + newthdot * _DT
        new = jnp.stack([newth, newthdot]).astype(jnp.float32)
        t = state.t + 1
        return (
            PendulumState(state=new, t=t),
            {"state": self._obs(new)},
            -costs.astype(jnp.float32),
            jnp.zeros((), bool),
            t >= self.max_episode_steps,
        )

    @property
    def observation_space(self):
        high = np.array([1.0, 1.0, _MAX_SPEED], dtype=np.float32)
        return gym.spaces.Dict(
            {"state": gym.spaces.Box(-high, high, dtype=np.float32)}
        )

    @property
    def action_space(self):
        return gym.spaces.Box(
            -_MAX_TORQUE, _MAX_TORQUE, shape=(1,), dtype=np.float32
        )
