"""Pure-JAX CartPole — dynamics parity with Gymnasium's `CartPole-v1`.

Same Euler-integrated cart-pole ODE, constants and termination thresholds
as `gymnasium/envs/classic_control/cartpole.py` (tested to tolerance in
`tests/test_envs/test_jax_envs.py`); the 500-step `TimeLimit` truncation of
the registered v1 spec is folded into the state's step counter. Computation
is float32 (the host env integrates in float64 and rounds the returned
observation to float32 — the per-step drift is below 1e-6)."""

from __future__ import annotations

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np

from ... import nn
from .core import JaxEnv

__all__ = ["CartPoleState", "JaxCartPole"]

_GRAVITY = 9.8
_MASSCART = 1.0
_MASSPOLE = 0.1
_TOTAL_MASS = _MASSPOLE + _MASSCART
_LENGTH = 0.5  # half the pole's length
_POLEMASS_LENGTH = _MASSPOLE * _LENGTH
_FORCE_MAG = 10.0
_TAU = 0.02
_THETA_THRESHOLD = 12 * 2 * np.pi / 360
_X_THRESHOLD = 2.4


class CartPoleState(nn.Module):
    state: jax.Array  # [4] f32: x, x_dot, theta, theta_dot
    t: jax.Array  # [] i32 steps since reset (TimeLimit counter)


class JaxCartPole(JaxEnv):
    max_episode_steps: int = nn.static(default=500)

    def reset(self, key):
        state = jax.random.uniform(key, (4,), jnp.float32, -0.05, 0.05)
        return CartPoleState(state=state, t=jnp.zeros((), jnp.int32)), {
            "state": state
        }

    def step(self, state: CartPoleState, action, key):
        del key  # deterministic dynamics; key kept for the uniform env API
        x, x_dot, theta, theta_dot = (
            state.state[0], state.state[1], state.state[2], state.state[3]
        )
        force = jnp.where(action == 1, _FORCE_MAG, -_FORCE_MAG)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        temp = (
            force + _POLEMASS_LENGTH * jnp.square(theta_dot) * sintheta
        ) / _TOTAL_MASS
        thetaacc = (_GRAVITY * sintheta - costheta * temp) / (
            _LENGTH * (4.0 / 3.0 - _MASSPOLE * jnp.square(costheta) / _TOTAL_MASS)
        )
        xacc = temp - _POLEMASS_LENGTH * thetaacc * costheta / _TOTAL_MASS
        # euler integrator (the gymnasium default)
        x = x + _TAU * x_dot
        x_dot = x_dot + _TAU * xacc
        theta = theta + _TAU * theta_dot
        theta_dot = theta_dot + _TAU * thetaacc
        new = jnp.stack([x, x_dot, theta, theta_dot]).astype(jnp.float32)
        t = state.t + 1
        terminated = (
            (jnp.abs(x) > _X_THRESHOLD) | (jnp.abs(theta) > _THETA_THRESHOLD)
        )
        truncated = t >= self.max_episode_steps
        reward = jnp.float32(1.0)
        return (
            CartPoleState(state=new, t=t),
            {"state": new},
            reward,
            terminated,
            truncated,
        )

    @property
    def observation_space(self):
        high = np.array(
            [_X_THRESHOLD * 2, np.inf, _THETA_THRESHOLD * 2, np.inf],
            dtype=np.float32,
        )
        return gym.spaces.Dict(
            {"state": gym.spaces.Box(-high, high, dtype=np.float32)}
        )

    @property
    def action_space(self):
        return gym.spaces.Discrete(2)
