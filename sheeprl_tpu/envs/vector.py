"""Vectorized environment runners (sync + async subprocess).

The framework's own replacement for `gym.vector.{Sync,Async}VectorEnv`
(used by every reference algorithm, /root/reference/sheeprl/algos/ppo/ppo.py:137-152)
with the semantics the training loops want, independent of gymnasium's
version-to-version autoreset changes:

  - **same-step autoreset**: when an env finishes, its final observation is
    surfaced as `infos[i]["final_observation"]` and the returned observation
    is already the reset one — the policy never sees a stale terminal obs;
  - **dict-obs batching**: observations arrive as `{key: [N, ...]}` numpy
    stacks, the exact host-side layout `jax.device_put` ships to HBM in one
    transfer per key;
  - **per-env info dicts**: `infos` is a list of length `num_envs` (episode
    stats from RecordEpisodeStatistics pass through untouched).

The async runner keeps one OS process per env (envs are CPU/GIL-bound
Python; stepping them in subprocesses overlaps with device compute exactly
like the reference's AsyncVectorEnv subprocesses did).
"""

from __future__ import annotations

import multiprocessing as mp
from typing import Any, Callable, Sequence

import gymnasium as gym
import numpy as np

__all__ = ["SyncVectorEnv", "AsyncVectorEnv", "make_vector_env"]


def _batch_obs(space: gym.Space, obs_list: Sequence[Any]):
    if isinstance(space, gym.spaces.Dict):
        return {k: np.stack([o[k] for o in obs_list]) for k in space.spaces}
    return np.stack(obs_list)


class _VectorEnvBase:
    single_observation_space: gym.Space
    single_action_space: gym.Space
    num_envs: int

    @property
    def observation_space(self):
        return self.single_observation_space

    @property
    def action_space(self):
        return self.single_action_space


class SyncVectorEnv(_VectorEnvBase):
    def __init__(self, env_fns: Sequence[Callable[[], gym.Env]]):
        self.envs = [fn() for fn in env_fns]
        self.num_envs = len(self.envs)
        self.single_observation_space = self.envs[0].observation_space
        self.single_action_space = self.envs[0].action_space

    def reset(self, seed: int | Sequence[int] | None = None):
        seeds = self._expand_seed(seed)
        obs_list, infos = [], []
        for env, s in zip(self.envs, seeds):
            obs, info = env.reset(seed=s)
            obs_list.append(obs)
            infos.append(info)
        return _batch_obs(self.single_observation_space, obs_list), infos

    def step(self, actions: Sequence[Any]):
        obs_list, rewards, terms, truncs, infos = [], [], [], [], []
        for env, act in zip(self.envs, actions):
            obs, reward, term, trunc, info = env.step(act)
            if term or trunc:
                info = dict(info)
                info["final_observation"] = obs
                obs, _ = env.reset()
            obs_list.append(obs)
            rewards.append(reward)
            terms.append(term)
            truncs.append(trunc)
            infos.append(info)
        return (
            _batch_obs(self.single_observation_space, obs_list),
            np.asarray(rewards, dtype=np.float32),
            np.asarray(terms, dtype=bool),
            np.asarray(truncs, dtype=bool),
            infos,
        )

    def close(self):
        for env in self.envs:
            env.close()

    def call(self, name: str, *args, **kwargs):
        return [getattr(env, name)(*args, **kwargs) for env in self.envs]

    def _expand_seed(self, seed):
        if seed is None or isinstance(seed, int):
            return [seed if seed is None else seed + i for i in range(self.num_envs)]
        return list(seed)


def _worker(remote, parent_remote, env_fn) -> None:
    parent_remote.close()
    if isinstance(env_fn, bytes):  # cloudpickled closure (spawn/forkserver path)
        import cloudpickle

        env_fn = cloudpickle.loads(env_fn)
    env = env_fn()
    try:
        while True:
            cmd, payload = remote.recv()
            if cmd == "reset":
                remote.send(env.reset(seed=payload))
            elif cmd == "step":
                obs, reward, term, trunc, info = env.step(payload)
                if term or trunc:
                    info = dict(info)
                    info["final_observation"] = obs
                    obs, _ = env.reset()
                remote.send((obs, reward, term, trunc, info))
            elif cmd == "spaces":
                remote.send((env.observation_space, env.action_space))
            elif cmd == "call":
                name, args, kwargs = payload
                remote.send(getattr(env, name)(*args, **kwargs))
            elif cmd == "close":
                env.close()
                remote.send(None)
                break
    except KeyboardInterrupt:
        pass
    finally:
        remote.close()


class AsyncVectorEnv(_VectorEnvBase):
    """Subprocess vector env. Defaults to the `spawn` start method: the
    parent is a multithreaded JAX process, and `fork`ing it can deadlock the
    child mid-step. Env thunks (closures) are shipped to spawned workers via
    cloudpickle. NOTE: as with any `spawn` usage, driver *scripts* must guard
    their entry point with `if __name__ == "__main__":`."""

    def __init__(self, env_fns: Sequence[Callable[[], gym.Env]], context: str = "spawn"):
        ctx = mp.get_context(context)
        self.num_envs = len(env_fns)
        if context in ("spawn", "forkserver"):
            import cloudpickle

            env_fns = [cloudpickle.dumps(fn) for fn in env_fns]
        self._remotes, self._work_remotes = zip(
            *[ctx.Pipe(duplex=True) for _ in range(self.num_envs)]
        )
        self._procs = []
        for work_remote, remote, fn in zip(self._work_remotes, self._remotes, env_fns):
            proc = ctx.Process(
                target=_worker, args=(work_remote, remote, fn), daemon=True
            )
            proc.start()
            work_remote.close()
            self._procs.append(proc)
        self._remotes[0].send(("spaces", None))
        self.single_observation_space, self.single_action_space = self._remotes[0].recv()
        self._closed = False

    def reset(self, seed: int | Sequence[int] | None = None):
        if seed is None or isinstance(seed, int):
            seeds = [seed if seed is None else seed + i for i in range(self.num_envs)]
        else:
            seeds = list(seed)
        for remote, s in zip(self._remotes, seeds):
            remote.send(("reset", s))
        results = [remote.recv() for remote in self._remotes]
        obs_list, infos = zip(*results)
        return _batch_obs(self.single_observation_space, obs_list), list(infos)

    def step(self, actions: Sequence[Any]):
        for remote, act in zip(self._remotes, actions):
            remote.send(("step", act))
        results = [remote.recv() for remote in self._remotes]
        obs_list, rewards, terms, truncs, infos = zip(*results)
        return (
            _batch_obs(self.single_observation_space, obs_list),
            np.asarray(rewards, dtype=np.float32),
            np.asarray(terms, dtype=bool),
            np.asarray(truncs, dtype=bool),
            list(infos),
        )

    def call(self, name: str, *args, **kwargs):
        for remote in self._remotes:
            remote.send(("call", (name, args, kwargs)))
        return [remote.recv() for remote in self._remotes]

    def close(self):
        if self._closed:
            return
        for remote in self._remotes:
            try:
                remote.send(("close", None))
                remote.recv()
            except (BrokenPipeError, EOFError):
                pass
        for proc in self._procs:
            proc.join(timeout=5)
        self._closed = True


def make_vector_env(
    env_fns: Sequence[Callable[[], gym.Env]], sync: bool = True
) -> _VectorEnvBase:
    return SyncVectorEnv(env_fns) if sync else AsyncVectorEnv(env_fns)
