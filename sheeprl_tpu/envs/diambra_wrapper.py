"""DIAMBRA arcade wrapper (capability target:
/root/reference/sheeprl/envs/diambra_wrapper.py — discrete/multidiscrete
action spaces, per-rank port offsetting). The `diambra` packages are not
present in this image; the wrapper raises an actionable error until the
backend is installed."""

from __future__ import annotations

try:
    import diambra.arena  # noqa: F401

    _DIAMBRA_AVAILABLE = True
except ImportError:
    _DIAMBRA_AVAILABLE = False


class DiambraWrapper:
    def __init__(self, *args, **kwargs):
        if not _DIAMBRA_AVAILABLE:
            raise ModuleNotFoundError(
                "diambra is not installed: `pip install diambra diambra-arena` "
                "(requires the DIAMBRA docker engine); env ids look like "
                "`diambra_doapp`"
            )
        raise NotImplementedError(
            "DIAMBRA wrapper pending implementation against an installed "
            "diambra backend (reference: sheeprl/envs/diambra_wrapper.py)"
        )
