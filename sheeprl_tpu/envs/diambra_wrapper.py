"""DIAMBRA Arena environment wrapper.

Capability parity with /root/reference/sheeprl/envs/diambra_wrapper.py:20-103
— arcade fighting games behind a dict observation space, discrete or
multidiscrete action spaces, settings/wrapper plumbing (sticky actions force
`step_ratio=1`, the engine's own frame-stack/dilation wrappers are disabled
in favor of the framework's), and per-rank engine instances (the reference
offsets engine ports by `rank`; here `rank` is forwarded to the backend's
`make`).

Design difference from the reference: the `diambra.arena` engine is reached
through an injectable *backend* object instead of module-level imports, so
the settings construction and observation conversion are unit-testable in CI
where the DIAMBRA engine (a licensed docker container) is absent — the same
strategy as `minedojo.py` / `minerl.py`.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Optional, Tuple, Union

import gymnasium
import numpy as np


class DiambraBackend:
    """Late-bound adapter over the real `diambra.arena` package."""

    def __init__(self):
        import diambra.arena  # deferred: needs the engine + ROMs

        self._arena = diambra.arena

    def make(self, env_id: str, settings: dict, wrappers: dict, seed, rank: int):
        return self._arena.make(env_id, settings, wrappers, seed=seed, rank=rank)


class DiambraWrapper(gymnasium.Env):
    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        env_id: str,
        action_space: str = "discrete",
        screen_size: Union[int, Tuple[int, int]] = 64,
        grayscale: bool = False,
        attack_but_combination: bool = True,
        actions_stack: int = 1,
        noop_max: int = 0,
        sticky_actions: int = 1,
        seed: Optional[int] = None,
        rank: int = 0,
        diambra_settings: Optional[Dict[str, Any]] = None,
        diambra_wrappers: Optional[Dict[str, Any]] = None,
        backend: Optional[Any] = None,
    ) -> None:
        super().__init__()
        if isinstance(screen_size, int):
            screen_size = (screen_size,) * 2

        settings = {
            **(diambra_settings or {}),
            "action_space": action_space,
            "attack_but_combination": attack_but_combination,
            "frame_shape": (*screen_size, int(1 * grayscale)),
        }
        # sticky actions repeat the same command N engine frames; a step
        # ratio > 1 would multiply the repeat (reference wrapper.py:47-52)
        if sticky_actions > 1:
            if settings.get("step_ratio", 2) > 1:
                warnings.warn(
                    "step_ratio forced to 1 because sticky actions are active "
                    f"({sticky_actions})"
                )
            settings["step_ratio"] = 1
        diambra_wrappers = dict(diambra_wrappers or {})
        # frame handling belongs to the framework pipeline (_ImageTransform /
        # FrameStack in utils/env.py), not the engine
        if diambra_wrappers.pop("frame_stack", None) is not None:
            warnings.warn("the DIAMBRA frame_stack wrapper is disabled")
        if diambra_wrappers.pop("dilation", None) is not None:
            warnings.warn("the DIAMBRA dilation wrapper is disabled")
        wrappers = {
            **diambra_wrappers,
            "no_op_max": noop_max,
            "flatten": True,
            "actions_stack": actions_stack,
            "sticky_actions": sticky_actions,
        }

        self._backend = backend if backend is not None else DiambraBackend()
        self._env = self._backend.make(env_id, settings, wrappers, seed, rank)

        self.action_space = (
            gymnasium.spaces.Discrete(self._env.action_space.n)
            if action_space == "discrete"
            else gymnasium.spaces.MultiDiscrete(self._env.action_space.nvec)
        )
        obs: Dict[str, gymnasium.spaces.Box] = {}
        for key, space in self._env.observation_space.spaces.items():
            if hasattr(space, "n"):  # engine-side Discrete -> 1-dim Box
                obs[key] = gymnasium.spaces.Box(0, space.n - 1, (1,), np.int32)
            elif hasattr(space, "low"):  # engine-side Box
                obs[key] = gymnasium.spaces.Box(
                    space.low, space.high, space.shape, space.dtype
                )
            else:
                raise RuntimeError(
                    f"invalid observation space for {key}: {type(space)}"
                )
        self.observation_space = gymnasium.spaces.Dict(obs)
        self.render_mode = "rgb_array"

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            key: np.asarray(value).reshape(self.observation_space[key].shape)
            for key, value in obs.items()
        }

    def step(self, action: Any):
        obs, reward, done, infos = self._env.step(action)
        infos["env_domain"] = "DIAMBRA"
        return self._convert_obs(obs), reward, done, False, infos

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        return self._convert_obs(self._env.reset()), {"env_domain": "DIAMBRA"}

    def render(self):
        return None

    def close(self):
        self._env.close()
        return super().close()
