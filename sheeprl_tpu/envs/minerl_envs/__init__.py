from .tasks import (
    CUSTOM_TASKS,
    ActionHead,
    RewardItem,
    TaskSpec,
    custom_navigate,
    custom_obtain_diamond,
    custom_obtain_iron_pickaxe,
)

__all__ = [
    "ActionHead",
    "CUSTOM_TASKS",
    "RewardItem",
    "TaskSpec",
    "custom_navigate",
    "custom_obtain_diamond",
    "custom_obtain_iron_pickaxe",
]
