"""Declarative MineRL custom-task definitions.

Capability parity with the reference's herobraine `EnvSpec` subclasses
(/root/reference/sheeprl/envs/minerl_envs/{backend,navigate,obtain}.py —
CustomNavigate, CustomObtainDiamond, CustomObtainIronPickaxe), redesigned as
*pure data*: a `TaskSpec` fully describes a task's action interface,
observables, rewards, and server configuration without importing `minerl`.
The spec is consumed two ways:

- `sheeprl_tpu.envs.minerl.MineRLBackend` compiles it into a real herobraine
  EnvSpec (handlers built lazily, only when the `minerl` package exists);
- `sheeprl_tpu.envs.minerl_mock.FakeMineRLBackend` interprets it directly,
  so the entire task surface (action enumeration, reward schedules, success
  rules) is unit-testable in CI with no JDK/Minecraft.

The data below mirrors the reference tasks field by field: the base keyboard
action set (backend.py:16), navigate's compass/place-dirt/touch-block reward
(navigate.py:30-78), and the obtain tasks' inventory observations, crafting
action vocabularies, and item reward schedules (obtain.py:53-259).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Sequence, Tuple

# keyboard keys every task shares (reference backend.py:16)
SIMPLE_KEYBOARD_ACTIONS = (
    "forward",
    "back",
    "left",
    "right",
    "jump",
    "sneak",
    "sprint",
    "attack",
)

NAVIGATE_STEPS = 6000


@dataclass(frozen=True)
class ActionHead:
    """One entry of the sim's dict action space.

    kind: "binary" (0/1 key press), "camera" ([pitch, yaw] degree deltas), or
    "enum" (categorical over `values`, first entry the no-op, reference
    encodes it as the herobraine Enum's "none").
    """

    key: str
    kind: str
    values: Tuple[str, ...] = ()

    def __post_init__(self):
        if self.kind not in ("binary", "camera", "enum"):
            raise ValueError(f"unknown action head kind: {self.kind}")
        if self.kind == "enum" and not self.values:
            raise ValueError(f"enum head {self.key} needs values")


@dataclass(frozen=True)
class RewardItem:
    """One row of an obtain-style reward schedule (obtain.py:169-182)."""

    item: str
    amount: int
    reward: float


def _base_heads() -> Tuple[ActionHead, ...]:
    return tuple(
        [ActionHead(k, "binary") for k in SIMPLE_KEYBOARD_ACTIONS]
        + [ActionHead("camera", "camera")]
    )


@dataclass(frozen=True)
class TaskSpec:
    """Complete description of a MineRL custom task."""

    name: str
    max_episode_steps: int
    # action interface: base keyboard+camera plus task-specific enum heads
    extra_heads: Tuple[ActionHead, ...] = ()
    # observables beyond pov/life-stats (backend.py:32-37)
    inventory_items: Tuple[str, ...] = ()
    has_compass: bool = False
    has_equipment: bool = False
    # rewards
    reward_schedule: Tuple[RewardItem, ...] = ()
    dense: bool = False  # dense: reward every collection, else once per item
    touch_block_rewards: Tuple[Tuple[str, float], ...] = ()  # navigate
    compass_distance_reward: float = 0.0  # navigate dense shaping, per block
    # episode-end conditions
    quit_on_touch_block: Tuple[str, ...] = ()
    quit_on_possess: Tuple[Tuple[str, int], ...] = ()
    quit_on_craft: Tuple[Tuple[str, int], ...] = ()
    # server / world configuration
    world_generator: str = "default"  # "default" | "biome:<id>"
    start_time: int = 6000
    allow_time_passage: bool = False
    allow_spawning: bool = False
    weather: Optional[str] = None
    starting_inventory: Tuple[Tuple[str, int], ...] = ()
    navigation_decorator: bool = False
    # success rule: reward threshold (navigate) or schedule coverage (obtain)
    success_reward_threshold: Optional[float] = None

    @property
    def action_heads(self) -> Tuple[ActionHead, ...]:
        return _base_heads() + self.extra_heads

    def determine_success(self, rewards: Sequence[float]) -> bool:
        """Reference success rules: navigate sums rewards against a threshold
        (navigate.py:90-94); obtain checks the set of distinct reward values
        covers the schedule up to 10% missing (obtain.py:151-160)."""
        if self.success_reward_threshold is not None:
            return sum(rewards) >= self.success_reward_threshold
        if self.reward_schedule:
            targets = {r.reward for r in self.reward_schedule}
            seen = targets.intersection(set(rewards))
            max_missing = round(len(self.reward_schedule) * 0.1)
            return len(seen) >= len(targets) - max_missing
        return False


# --- navigate (reference navigate.py:19-94) ----------------------------------


def custom_navigate(dense: bool = False, extreme: bool = False) -> TaskSpec:
    suffix = ("Extreme" if extreme else "") + ("Dense" if dense else "")
    return TaskSpec(
        name=f"CustomMineRLNavigate{suffix}-v0",
        max_episode_steps=NAVIGATE_STEPS,
        extra_heads=(ActionHead("place", "enum", ("none", "dirt")),),
        inventory_items=("dirt",),
        has_compass=True,
        dense=dense,
        touch_block_rewards=(("diamond_block", 100.0),),
        compass_distance_reward=1.0 if dense else 0.0,
        quit_on_touch_block=("diamond_block",),
        world_generator="biome:3" if extreme else "default",
        start_time=6000,
        allow_time_passage=False,
        allow_spawning=False,
        weather="clear",
        starting_inventory=(("compass", 1),),
        navigation_decorator=True,
        success_reward_threshold=160.0 if dense else 100.0,
    )


# --- obtain family (reference obtain.py:24-259) ------------------------------

_OBTAIN_INVENTORY = (
    "dirt",
    "coal",
    "torch",
    "log",
    "planks",
    "stick",
    "crafting_table",
    "wooden_axe",
    "wooden_pickaxe",
    "stone",
    "cobblestone",
    "furnace",
    "stone_axe",
    "stone_pickaxe",
    "iron_ore",
    "iron_ingot",
    "iron_axe",
    "iron_pickaxe",
)

_OBTAIN_HEADS = (
    ActionHead(
        "place",
        "enum",
        ("none", "dirt", "stone", "cobblestone", "crafting_table", "furnace", "torch"),
    ),
    ActionHead(
        "equip",
        "enum",
        (
            "none",
            "air",
            "wooden_axe",
            "wooden_pickaxe",
            "stone_axe",
            "stone_pickaxe",
            "iron_axe",
            "iron_pickaxe",
        ),
    ),
    ActionHead("craft", "enum", ("none", "torch", "stick", "planks", "crafting_table")),
    ActionHead(
        "nearbyCraft",
        "enum",
        (
            "none",
            "wooden_axe",
            "wooden_pickaxe",
            "stone_axe",
            "stone_pickaxe",
            "iron_axe",
            "iron_pickaxe",
            "furnace",
        ),
    ),
    ActionHead("nearbySmelt", "enum", ("none", "iron_ingot", "coal")),
)

_IRON_SCHEDULE = (
    RewardItem("log", 1, 1),
    RewardItem("planks", 1, 2),
    RewardItem("stick", 1, 4),
    RewardItem("crafting_table", 1, 4),
    RewardItem("wooden_pickaxe", 1, 8),
    RewardItem("cobblestone", 1, 16),
    RewardItem("furnace", 1, 32),
    RewardItem("stone_pickaxe", 1, 32),
    RewardItem("iron_ore", 1, 64),
    RewardItem("iron_ingot", 1, 128),
    RewardItem("iron_pickaxe", 1, 256),
)


def _obtain_base(name: str, dense: bool, max_episode_steps: int) -> TaskSpec:
    return TaskSpec(
        name=name,
        max_episode_steps=max_episode_steps,
        extra_heads=_OBTAIN_HEADS,
        inventory_items=_OBTAIN_INVENTORY,
        has_equipment=True,
        dense=dense,
        start_time=6000,
        allow_time_passage=True,
        allow_spawning=True,
    )


def custom_obtain_diamond(dense: bool = False) -> TaskSpec:
    suffix = "Dense" if dense else ""
    return replace(
        _obtain_base(f"CustomMineRLObtainDiamond{suffix}-v0", dense, 18000),
        reward_schedule=_IRON_SCHEDULE + (RewardItem("diamond", 1, 1024),),
        quit_on_possess=(("diamond", 1),),
    )


def custom_obtain_iron_pickaxe(dense: bool = False) -> TaskSpec:
    suffix = "Dense" if dense else ""
    return replace(
        _obtain_base(f"CustomMineRLObtainIronPickaxe{suffix}-v0", dense, 6000),
        reward_schedule=_IRON_SCHEDULE,
        quit_on_craft=(("iron_pickaxe", 1),),
    )


CUSTOM_TASKS = {
    "custom_navigate": custom_navigate,
    "custom_obtain_diamond": custom_obtain_diamond,
    "custom_obtain_iron_pickaxe": custom_obtain_iron_pickaxe,
}
