"""MineDojo wrapper (capability target:
/root/reference/sheeprl/envs/minedojo.py — 19-action functional map +
3-head MultiDiscrete, `mask_*` action-mask obs keys, pitch/yaw limits,
sticky attack/jump). The `minedojo` package is not present in this image;
the wrapper raises an actionable error until the backend is installed."""

from __future__ import annotations

try:
    import minedojo  # noqa: F401

    _MINEDOJO_AVAILABLE = True
except ImportError:
    _MINEDOJO_AVAILABLE = False


class MineDojoWrapper:
    def __init__(self, *args, **kwargs):
        if not _MINEDOJO_AVAILABLE:
            raise ModuleNotFoundError(
                "minedojo is not installed: `pip install minedojo` (requires "
                "JDK 8); env ids look like `minedojo_open-ended`"
            )
        raise NotImplementedError(
            "MineDojo wrapper pending implementation against an installed "
            "minedojo backend (reference: sheeprl/envs/minedojo.py)"
        )
