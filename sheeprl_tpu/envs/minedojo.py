"""MineDojo environment wrapper.

Capability parity with /root/reference/sheeprl/envs/minedojo.py:60-284 — the
19-action functional map compiled down to MineDojo's native 8-dim action
vector, a 3-head MultiDiscrete action space (functional action, craft
argument, equip/place/destroy argument), action-validity masks exposed as
`mask_*` observation keys (consumed by `MinedojoActor`), pitch limits, and
sticky attack/jump.

Design differences from the reference (besides being a fresh implementation):

- The MineDojo simulator is reached through an injectable *backend* object
  instead of a module-level import, so the full action/observation mapping is
  unit-testable in CI where the `minedojo` package (and a JDK) is absent.
- The action translation (sticky state + table lookup) lives in a standalone
  `ActionTranslator`, independent of the env plumbing.
- Sticky attack *resets its counter* when another functional action is chosen;
  the reference instead permanently disables sticky attack for the rest of the
  episode (reference minedojo.py:186 writes `self._sticky_attack = 0`), which
  reads as a bug rather than intent.
- Choosing equip/place/destroy for an item not in the inventory falls back to
  slot 0 (a no-op for the sim) instead of raising KeyError; masked policies
  never hit this path, unmasked random exploration does.

MineDojo's native action vector (see the MineDojo sim docs):
  [0] move fwd/back (0 noop, 1 forward, 2 back)
  [1] move left/right (0 noop, 1 left, 2 right)
  [2] jump/sneak/sprint (0 noop, 1 jump, 2 sneak, 3 sprint)
  [3] camera pitch bucket (0..24; 12 noop; 15 degrees per step)
  [4] camera yaw bucket (0..24; 12 noop; 15 degrees per step)
  [5] functional (0 noop, 1 use, 2 drop, 3 attack, 4 craft, 5 equip,
      6 place, 7 destroy)
  [6] craft argument (index into the craft/smelt vocabulary)
  [7] inventory-slot argument (for equip/place/destroy)
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium as gym
import numpy as np

# functional-action codes in slot [5] of the native vector
_FN_NOOP, _FN_USE, _FN_DROP, _FN_ATTACK, _FN_CRAFT = 0, 1, 2, 3, 4
_FN_EQUIP, _FN_PLACE, _FN_DESTROY = 5, 6, 7

N_HIGH_LEVEL_ACTIONS = 19
_CAMERA_NOOP = 12  # bucket 12 of 0..24 = no rotation
_CAMERA_STEP_DEGREES = 15.0


def build_action_table() -> np.ndarray:
    """The [19, 8] table mapping high-level action ids to native vectors:
    0 noop; 1-7 movement combos; 8-11 camera; 12-18 functional actions
    (reference minedojo.py:16-36)."""
    table = np.zeros((N_HIGH_LEVEL_ACTIONS, 8), dtype=np.int64)
    table[:, 3] = table[:, 4] = _CAMERA_NOOP
    table[1, 0] = 1  # forward
    table[2, 0] = 2  # back
    table[3, 1] = 1  # left
    table[4, 1] = 2  # right
    table[5, 0], table[5, 2] = 1, 1  # jump + forward
    table[6, 0], table[6, 2] = 1, 2  # sneak + forward
    table[7, 0], table[7, 2] = 1, 3  # sprint + forward
    table[8, 3] = _CAMERA_NOOP - 1  # pitch down 15 degrees
    table[9, 3] = _CAMERA_NOOP + 1  # pitch up 15 degrees
    table[10, 4] = _CAMERA_NOOP - 1  # yaw left 15 degrees
    table[11, 4] = _CAMERA_NOOP + 1  # yaw right 15 degrees
    for high_id, fn in zip(range(12, 19), range(_FN_USE, _FN_DESTROY + 1)):
        table[high_id, 5] = fn
    return table


ACTION_TABLE = build_action_table()


@dataclass
class ActionTranslator:
    """Compiles (functional_id, craft_arg, item_arg) triples into native
    8-dim actions, carrying the sticky attack/jump counters across steps
    (reference minedojo.py:172-213)."""

    sticky_attack: int = 30
    sticky_jump: int = 10
    attack_counter: int = 0
    jump_counter: int = 0

    def reset(self) -> None:
        self.attack_counter = 0
        self.jump_counter = 0

    def translate(
        self,
        action: Sequence[int],
        slot_of_item: Dict[int, int],
    ) -> np.ndarray:
        """`action` = the 3-head MultiDiscrete sample; `slot_of_item` maps an
        item vocabulary id to the inventory slot currently holding it."""
        native = ACTION_TABLE[int(action[0])].copy()

        if self.sticky_attack:
            if native[5] == _FN_ATTACK:
                self.attack_counter = self.sticky_attack - 1
            elif native[5] == _FN_NOOP and self.attack_counter > 0:
                native[5] = _FN_ATTACK
                self.attack_counter -= 1
            elif native[5] != _FN_ATTACK:
                self.attack_counter = 0

        if self.sticky_jump:
            if native[2] == 1:  # jump chosen
                self.jump_counter = self.sticky_jump - 1
            elif native[2] == 0 and self.jump_counter > 0:
                native[2] = 1
                # keep moving while the sticky jump plays out: repeated
                # standing jumps go nowhere, so default to forward
                if native[0] == 0 and native[1] == 0:
                    native[0] = 1
                self.jump_counter -= 1
            elif native[2] != 1:
                self.jump_counter = 0

        native[6] = int(action[1]) if native[5] == _FN_CRAFT else 0
        if native[5] in (_FN_EQUIP, _FN_PLACE, _FN_DESTROY):
            native[7] = slot_of_item.get(int(action[2]), 0)
        else:
            native[7] = 0
        return native


class MineDojoBackend:
    """Late-bound adapter over the real `minedojo` package. Tests substitute
    an instance with a tiny item vocabulary and a scripted sim."""

    def __init__(self):
        import minedojo  # deferred: needs the package + JDK
        from minedojo.sim import ALL_CRAFT_SMELT_ITEMS, ALL_ITEMS

        self._minedojo = minedojo
        self.all_items = ["_".join(item.split(" ")) for item in ALL_ITEMS]
        self.craft_smelt_items = list(ALL_CRAFT_SMELT_ITEMS)

    def make(self, task_id: str, **kwargs) -> Any:
        return self._minedojo.make(task_id=task_id, **kwargs)


class MineDojoWrapper(gym.Env):
    """Gymnasium-facing MineDojo env with dict observations, action masks,
    pitch limiting, and the 3-head MultiDiscrete action interface."""

    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        task_id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: int = 30,
        sticky_jump: int = 10,
        backend: Optional[Any] = None,
        **kwargs: Any,
    ):
        self._height = height
        self._width = width
        self._pitch_limits = pitch_limits
        self._start_position = copy.deepcopy(kwargs.pop("start_position", None))
        self._break_speed_multiplier = kwargs.pop("break_speed_multiplier", 100)
        if self._start_position is not None and not (
            pitch_limits[0] <= self._start_position["pitch"] <= pitch_limits[1]
        ):
            raise ValueError(
                f"start pitch {self._start_position['pitch']} outside pitch "
                f"limits {pitch_limits}"
            )

        self._backend = backend if backend is not None else MineDojoBackend()
        self._items = list(self._backend.all_items)
        self._craft_items = list(self._backend.craft_smelt_items)
        self._item_id = {name: i for i, name in enumerate(self._items)}
        self.n_items = len(self._items)

        self._sim = self._backend.make(
            task_id,
            image_size=(height, width),
            world_seed=seed,
            start_position=self._start_position,
            generate_world_type="default",
            fast_reset=True,
            break_speed_multiplier=self._break_speed_multiplier,
            **kwargs,
        )
        self._translator = ActionTranslator(
            sticky_attack=sticky_attack, sticky_jump=sticky_jump
        )
        # per-item-id first inventory slot, rebuilt from every observation
        self._slot_of_item: Dict[int, int] = {}
        self._inventory_names: np.ndarray = np.array([], dtype=object)
        self._inventory_max = np.zeros(self.n_items, dtype=np.float32)
        self._pos: Dict[str, float] = {}

        n_items, n_craft = self.n_items, len(self._craft_items)
        rgb_shape = self._sim.observation_space["rgb"].shape
        self.action_space = gym.spaces.MultiDiscrete(
            np.array([N_HIGH_LEVEL_ACTIONS, n_craft, n_items])
        )
        self.observation_space = gym.spaces.Dict(
            {
                "rgb": gym.spaces.Box(0, 255, rgb_shape, np.uint8),
                "inventory": gym.spaces.Box(0.0, np.inf, (n_items,), np.float32),
                "inventory_max": gym.spaces.Box(0.0, np.inf, (n_items,), np.float32),
                "inventory_delta": gym.spaces.Box(
                    -np.inf, np.inf, (n_items,), np.float32
                ),
                "equipment": gym.spaces.Box(0.0, 1.0, (n_items,), np.int32),
                "life_stats": gym.spaces.Box(
                    0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32
                ),
                "mask_action_type": gym.spaces.Box(
                    0, 1, (N_HIGH_LEVEL_ACTIONS,), bool
                ),
                "mask_equip/place": gym.spaces.Box(0, 1, (n_items,), bool),
                "mask_destroy": gym.spaces.Box(0, 1, (n_items,), bool),
                "mask_craft_smelt": gym.spaces.Box(0, 1, (n_craft,), bool),
            }
        )
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # ---- observation conversion ---------------------------------------------

    def _canonical(self, item: str) -> str:
        return "_".join(item.split(" "))

    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        counts = np.zeros(self.n_items, dtype=np.float32)
        self._slot_of_item = {}
        names = [self._canonical(n) for n in list(inventory["name"])]
        self._inventory_names = np.array(names, dtype=object)
        for slot, (name, quantity) in enumerate(zip(names, inventory["quantity"])):
            item_id = self._item_id[name]
            # remember the FIRST slot holding each item (equip/place/destroy arg)
            self._slot_of_item.setdefault(item_id, slot)
            counts[item_id] += float(quantity)
        self._inventory_max = np.maximum(counts, self._inventory_max)
        return counts

    def _convert_inventory_delta(self, delta: Dict[str, Any]) -> np.ndarray:
        out = np.zeros(self.n_items, dtype=np.float32)
        for names_key, qty_key, sign in (
            ("inc_name_by_craft", "inc_quantity_by_craft", 1.0),
            ("dec_name_by_craft", "dec_quantity_by_craft", -1.0),
            ("inc_name_by_other", "inc_quantity_by_other", 1.0),
            ("dec_name_by_other", "dec_quantity_by_other", -1.0),
        ):
            for name, quantity in zip(delta[names_key], delta[qty_key]):
                out[self._item_id[self._canonical(name)]] += sign * float(quantity)
        return out

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        one_hot = np.zeros(self.n_items, dtype=np.int32)
        one_hot[self._item_id[self._canonical(equipment["name"][0])]] = 1
        return one_hot

    def _convert_masks(self, masks: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Project the sim's per-slot equip/destroy masks onto the item
        vocabulary and gate the functional actions that would have no valid
        argument (reference minedojo.py:156-170)."""
        equip_mask = np.zeros(self.n_items, dtype=bool)
        destroy_mask = np.zeros(self.n_items, dtype=bool)
        for name, can_equip, can_destroy in zip(
            self._inventory_names, masks["equip"], masks["destroy"]
        ):
            item_id = self._item_id[name]
            equip_mask[item_id] |= bool(can_equip)
            destroy_mask[item_id] |= bool(can_destroy)
        fn_mask = np.asarray(masks["action_type"], dtype=bool).copy()
        fn_mask[_FN_EQUIP] &= equip_mask.any()
        fn_mask[_FN_PLACE] &= equip_mask.any()
        fn_mask[_FN_DESTROY] &= destroy_mask.any()
        action_type = np.concatenate(
            [np.ones(12, dtype=bool), fn_mask[_FN_USE:]]  # movement/camera free
        )
        return {
            "mask_action_type": action_type,
            "mask_equip/place": equip_mask,
            "mask_destroy": destroy_mask,
            "mask_craft_smelt": np.asarray(masks["craft_smelt"], dtype=bool),
        }

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        return {
            "rgb": np.asarray(obs["rgb"]).copy(),
            "inventory": self._convert_inventory(obs["inventory"]),
            "inventory_max": self._inventory_max.copy(),
            "inventory_delta": self._convert_inventory_delta(obs["delta_inv"]),
            "equipment": self._convert_equipment(obs["equipment"]),
            "life_stats": np.concatenate(
                [
                    np.asarray(obs["life_stats"]["life"], dtype=np.float32).reshape(-1),
                    np.asarray(obs["life_stats"]["food"], dtype=np.float32).reshape(-1),
                    np.asarray(obs["life_stats"]["oxygen"], dtype=np.float32).reshape(-1),
                ]
            ),
            **self._convert_masks(obs["masks"]),
        }

    def _track_position(self, obs: Dict[str, Any]) -> None:
        loc = obs["location_stats"]
        self._pos = {
            "x": float(loc["pos"][0]),
            "y": float(loc["pos"][1]),
            "z": float(loc["pos"][2]),
            "pitch": float(np.asarray(loc["pitch"]).item()),
            "yaw": float(np.asarray(loc["yaw"]).item()),
        }

    def _info(self, obs: Dict[str, Any], action=None) -> Dict[str, Any]:
        info = {
            "life_stats": {
                "life": float(np.asarray(obs["life_stats"]["life"]).item()),
                "oxygen": float(np.asarray(obs["life_stats"]["oxygen"]).item()),
                "food": float(np.asarray(obs["life_stats"]["food"]).item()),
            },
            "location_stats": copy.deepcopy(self._pos),
            "biomeid": float(np.asarray(obs["location_stats"]["biome_id"]).item()),
        }
        if action is not None:
            info["action"] = list(np.asarray(action).tolist())
        return info

    # ---- gym API ------------------------------------------------------------

    def step(self, action: np.ndarray):
        requested = np.asarray(action)
        native = self._translator.translate(requested, self._slot_of_item)
        next_pitch = self._pos["pitch"] + (
            (native[3] - _CAMERA_NOOP) * _CAMERA_STEP_DEGREES
        )
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            native[3] = _CAMERA_NOOP

        obs, reward, done, _ = self._sim.step(native)
        self._track_position(obs)
        return self._convert_obs(obs), reward, done, False, self._info(obs, requested)

    def reset(self, seed=None, options=None):
        obs = self._sim.reset()
        self._track_position(obs)
        self._translator.reset()
        self._inventory_max = np.zeros(self.n_items, dtype=np.float32)
        return self._convert_obs(obs), self._info(obs)

    def render(self):
        return None

    def close(self):
        self._sim.close()
        return super().close()
