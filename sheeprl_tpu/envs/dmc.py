"""dm_control -> gymnasium bridge (pixels or flat states).

Capability parity with /root/reference/sheeprl/envs/dmc.py: dm_env spec ->
Box conversion, [-1, 1] normalized actions rescaled to the true action
bounds, frame-skip with early stop, physics-state info. Pixels are emitted
channel-LAST `[H, W, 3]` (the framework's NHWC convention; the reference
defaults to channel-first for torch).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

try:
    from dm_control import suite
    from dm_env import specs

    _DMC_AVAILABLE = True
except ImportError:  # pragma: no cover - exercised only without dm_control
    _DMC_AVAILABLE = False

import gymnasium as gym
from gymnasium import spaces

__all__ = ["DMCWrapper"]


def _spec_to_box(spec_list, dtype) -> spaces.Box:
    mins, maxs = [], []
    for s in spec_list:
        dim = int(np.prod(s.shape))
        if isinstance(s, specs.BoundedArray):
            mins.append(np.broadcast_to(s.minimum, (dim,)).astype(np.float32))
            maxs.append(np.broadcast_to(s.maximum, (dim,)).astype(np.float32))
        elif isinstance(s, specs.Array):
            maxs.append(np.full(dim, np.inf, dtype=np.float32))
            mins.append(np.full(dim, -np.inf, dtype=np.float32))
        else:
            raise ValueError(f"unrecognized spec: {type(s)}")
    low = np.concatenate(mins).astype(dtype)
    high = np.concatenate(maxs).astype(dtype)
    return spaces.Box(low, high, dtype=dtype)


def _flatten_obs(obs: dict) -> np.ndarray:
    pieces = [
        np.array([v]) if np.isscalar(v) else np.asarray(v).ravel()
        for v in obs.values()
    ]
    return np.concatenate(pieces, axis=0)


class DMCWrapper(gym.Env):
    def __init__(
        self,
        domain_name: str,
        task_name: str,
        from_pixels: bool = False,
        height: int = 84,
        width: int = 84,
        camera_id: int = 0,
        frame_skip: int = 1,
        task_kwargs: Optional[dict] = None,
        environment_kwargs: Optional[dict] = None,
        visualize_reward: bool = False,
        seed: Optional[int] = None,
    ):
        if not _DMC_AVAILABLE:
            raise ModuleNotFoundError(
                "dm_control is required for DMC environments"
            )
        self._from_pixels = from_pixels
        self._height = height
        self._width = width
        self._camera_id = camera_id
        self._frame_skip = frame_skip
        task_kwargs = dict(task_kwargs or {})
        if seed is not None:
            task_kwargs.setdefault("random", seed)
        self._env = suite.load(
            domain_name=domain_name,
            task_name=task_name,
            task_kwargs=task_kwargs,
            visualize_reward=visualize_reward,
            environment_kwargs=environment_kwargs,
        )
        self._true_action_space = _spec_to_box([self._env.action_spec()], np.float32)
        self._norm_action_space = spaces.Box(
            -1.0, 1.0, shape=self._true_action_space.shape, dtype=np.float32
        )
        if from_pixels:
            self._observation_space = spaces.Box(
                0, 255, shape=(height, width, 3), dtype=np.uint8
            )
        else:
            self._observation_space = _spec_to_box(
                self._env.observation_spec().values(), np.float64
            )
        self._state_space = _spec_to_box(
            self._env.observation_spec().values(), np.float64
        )
        self.current_state: np.ndarray | None = None
        self._render_mode = "rgb_array"
        self.seed(seed)

    # -- spaces --------------------------------------------------------------
    @property
    def observation_space(self):
        return self._observation_space

    @property
    def state_space(self):
        return self._state_space

    @property
    def action_space(self):
        return self._norm_action_space

    @property
    def reward_range(self):
        return 0, self._frame_skip

    @property
    def render_mode(self) -> str:
        return self._render_mode

    def seed(self, seed: Optional[int] = None):
        self._true_action_space.seed(seed)
        self._norm_action_space.seed(seed)
        self._observation_space.seed(seed)

    # -- helpers -------------------------------------------------------------
    def _get_obs(self, time_step) -> np.ndarray:
        if self._from_pixels:
            return self.render()
        return _flatten_obs(time_step.observation)

    def _denormalize_action(self, action: np.ndarray) -> np.ndarray:
        action = action.astype(np.float64)
        true_delta = self._true_action_space.high - self._true_action_space.low
        norm_delta = self._norm_action_space.high - self._norm_action_space.low
        action = (action - self._norm_action_space.low) / norm_delta
        return (action * true_delta + self._true_action_space.low).astype(np.float32)

    # -- gym API -------------------------------------------------------------
    def step(self, action):
        assert self._norm_action_space.contains(action)
        action = self._denormalize_action(action)
        reward, done = 0.0, False
        info: dict[str, Any] = {"internal_state": self._env.physics.get_state().copy()}
        time_step = None
        for _ in range(self._frame_skip):
            time_step = self._env.step(action)
            reward += time_step.reward or 0.0
            done = time_step.last()
            if done:
                break
        obs = self._get_obs(time_step)
        self.current_state = _flatten_obs(time_step.observation)
        info["discount"] = time_step.discount
        return obs, reward, done, False, info

    def reset(self, *, seed: Optional[int] = None, options: Optional[dict] = None):
        time_step = self._env.reset()
        self.current_state = _flatten_obs(time_step.observation)
        return self._get_obs(time_step), {}

    def render(self):
        return self._env.physics.render(
            height=self._height, width=self._width, camera_id=self._camera_id
        )

    def close(self):
        self._env.close()
        return super().close()
