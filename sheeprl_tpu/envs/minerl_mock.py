"""Scripted stand-in for the MineRL simulator.

Same philosophy as `minedojo_mock.py`: the reference ships deterministic
dummy envs as its CI backend (/root/reference/sheeprl/envs/dummy.py); this
extends that to MineRL, whose real backend needs a JDK + Minecraft. The fake
sim consumes the declarative `TaskSpec`, validates every dict action against
the spec's action heads (keys, enum vocabularies, camera shape), emits
observations in the exact nested format the real 0.4.4 sim produces
(pov, life_stats, inventory dict, compass angle, equipped_items), and records
actions for assertions — so `MineRLWrapper`'s full mapping runs in CI
unmodified.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .minerl_envs.tasks import TaskSpec

# small vocabulary standing in for minerl's mc.ALL_ITEMS; "iron ore" keeps a
# space to exercise the space->underscore canonicalization
MOCK_ALL_ITEMS = [
    "air",
    "dirt",
    "log",
    "planks",
    "stick",
    "crafting_table",
    "wooden_pickaxe",
    "cobblestone",
    "iron ore",
    "iron_pickaxe",
    "compass",
    "other",
]


class FakeMineRLSim:
    """Deterministic sim: scripted inventory/compass trajectories, episodes
    end after `episode_length` steps with a touch-block style reward."""

    def __init__(
        self,
        spec: TaskSpec,
        resolution=(64, 64),
        episode_length: int = 16,
        inventory: Optional[Dict[str, int]] = None,
    ):
        self.spec = spec
        self._h, self._w = resolution
        self._episode_length = episode_length
        self._t = 0
        self._initial_inventory = dict(
            inventory
            if inventory is not None
            else {"air": 2, "dirt": 3, "wooden_pickaxe": 1, "iron ore": 2}
        )
        self._inventory = dict(self._initial_inventory)
        self._equipped = "wooden_pickaxe"
        self.received_actions: List[Dict[str, Any]] = []

    def _obs(self) -> Dict[str, Any]:
        obs: Dict[str, Any] = {
            "pov": np.full((self._h, self._w, 3), self._t % 255, dtype=np.uint8),
            "life_stats": {
                "life": np.array([20.0]),
                "food": np.array([20.0]),
                "air": np.array([300.0]),
            },
            "inventory": dict(self._inventory),
        }
        if self.spec.has_compass:
            obs["compass"] = {"angle": np.array([45.0 - self._t])}
        if self.spec.has_equipment:
            obs["equipped_items"] = {"mainhand": {"type": self._equipped}}
        return obs

    def _validate(self, action: Dict[str, Any]) -> None:
        heads = {h.key: h for h in self.spec.action_heads}
        if set(action) != set(heads):
            raise ValueError(
                f"action keys {sorted(action)} != spec keys {sorted(heads)}"
            )
        for key, value in action.items():
            head = heads[key]
            if head.kind == "enum" and value not in head.values:
                raise ValueError(f"invalid enum value {value!r} for {key}")
            if head.kind == "camera" and np.asarray(value).shape != (2,):
                raise ValueError(f"camera action must be [pitch, yaw], got {value!r}")
            if head.kind == "binary" and int(value) not in (0, 1):
                raise ValueError(f"binary action {key} must be 0/1, got {value!r}")

    def reset(self) -> Dict[str, Any]:
        self._t = 0
        self._inventory = dict(self._initial_inventory)
        self._equipped = "wooden_pickaxe"
        return self._obs()

    def step(self, action: Dict[str, Any]):
        self._validate(action)
        self.received_actions.append(
            {
                k: (np.asarray(v).copy() if isinstance(v, np.ndarray) else v)
                for k, v in action.items()
            }
        )
        self._t += 1
        # scripted dynamics: picking up dirt every step with "attack" held
        if action.get("attack"):
            self._inventory["dirt"] = self._inventory.get("dirt", 0) + 1
        if action.get("equip", "none") != "none":
            self._equipped = action["equip"]
        done = self._t >= self._episode_length
        reward = 100.0 if done else (1.0 if self.spec.dense else 0.0)
        return self._obs(), reward, done, {}

    def close(self) -> None:
        pass


class FakeMineRLBackend:
    """Backend object compatible with MineRLWrapper(backend=...)."""

    def __init__(self, episode_length: int = 16, inventory=None):
        self.all_items = list(MOCK_ALL_ITEMS)
        self._episode_length = episode_length
        self._inventory = inventory
        self.last_sim: Optional[FakeMineRLSim] = None
        self.last_make_kwargs: Dict[str, Any] = {}

    def make(self, spec: TaskSpec, resolution=(64, 64), break_speed=100, seed=None):
        self.last_make_kwargs = dict(
            spec=spec, resolution=resolution, break_speed=break_speed, seed=seed
        )
        self.last_sim = FakeMineRLSim(
            spec,
            resolution=resolution,
            episode_length=self._episode_length,
            inventory=self._inventory,
        )
        return self.last_sim
