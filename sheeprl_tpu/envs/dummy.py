"""Deterministic dummy envs — the CI test backend (reachable via
`env_id=*_dummy`), mirroring /root/reference/sheeprl/envs/dummy.py but with
channel-LAST `[H, W, C]` uint8 image observations (the framework's NHWC
convention)."""

from __future__ import annotations

from typing import Sequence

import gymnasium as gym
import numpy as np


class _DummyBase(gym.Env):
    def __init__(self, size: tuple[int, int, int] = (64, 64, 3), n_steps: int = 4):
        self.observation_space = gym.spaces.Box(0, 255, shape=size, dtype=np.uint8)
        self.reward_range = (-np.inf, np.inf)
        self._current_step = 0
        self._n_steps = n_steps
        self._rng = np.random.default_rng(0)

    def _obs(self) -> np.ndarray:
        return self._rng.integers(
            0, 256, self.observation_space.shape, dtype=np.uint8
        )

    def step(self, action):
        done = self._current_step == self._n_steps
        self._current_step += 1
        return self._obs(), 0.0, done, False, {}

    def reset(self, seed=None, options=None):
        self._current_step = 0
        return np.zeros(self.observation_space.shape, dtype=np.uint8), {}

    def render(self):
        return np.zeros(self.observation_space.shape, dtype=np.uint8)

    def close(self):
        pass


class ContinuousDummyEnv(_DummyBase):
    def __init__(self, action_dim: int = 2, size=(64, 64, 3), n_steps: int = 4):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.Box(-np.inf, np.inf, shape=(action_dim,))


class DiscreteDummyEnv(_DummyBase):
    def __init__(self, action_dim: int = 2, size=(64, 64, 3), n_steps: int = 4):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.Discrete(action_dim)


class MultiDiscreteDummyEnv(_DummyBase):
    def __init__(self, action_dims: Sequence[int] = (2, 2), size=(64, 64, 3), n_steps: int = 4):
        super().__init__(size, n_steps)
        self.action_space = gym.spaces.MultiDiscrete(list(action_dims))
