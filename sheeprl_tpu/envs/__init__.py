from .dummy import ContinuousDummyEnv, DiscreteDummyEnv, MultiDiscreteDummyEnv
from .vector import AsyncVectorEnv, SyncVectorEnv, make_vector_env
from .wrappers import (
    ActionRepeat,
    DictObservation,
    FrameStack,
    MaskVelocityWrapper,
    RestartOnException,
)

__all__ = [
    "ContinuousDummyEnv",
    "DiscreteDummyEnv",
    "MultiDiscreteDummyEnv",
    "SyncVectorEnv",
    "AsyncVectorEnv",
    "make_vector_env",
    "ActionRepeat",
    "DictObservation",
    "FrameStack",
    "MaskVelocityWrapper",
    "RestartOnException",
]
