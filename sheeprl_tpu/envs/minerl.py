"""MineRL 0.4.4 environment wrapper.

Capability parity with /root/reference/sheeprl/envs/minerl.py:47-209 — a flat
Discrete action space enumerated from the task's dict action interface (one
entry per key press / camera quadrant / enum value, jump/sneak/sprint bundled
with forward), sticky attack/jump, pitch limits with yaw wrap-around, and
dict observations (rgb, life_stats, inventory, max_inventory, optional
compass/equipment over the full item vocabulary).

Design differences from the reference (a fresh implementation, not a port):

- Tasks are declarative `TaskSpec` data (`minerl_envs/tasks.py`) instead of
  herobraine `EnvSpec` subclasses; the sim is reached through an injectable
  *backend* object so the full action/observation mapping is unit-testable in
  CI where the `minerl` package (and a JDK) is absent — the same strategy as
  `sheeprl_tpu/envs/minedojo.py`.
- Images stay `[H, W, C]` (the framework's NHWC-native convention); the
  reference transposes to channel-first (minerl.py:159).
- The reference counts one unit of "air" per inventory *entry* rather than
  its quantity (minerl.py:149-152); that quirk is kept for behavioral parity.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

import gymnasium as gym
import numpy as np

from .minerl_envs.tasks import CUSTOM_TASKS, TaskSpec

CAMERA_DELTAS = (
    np.array([-15.0, 0.0]),
    np.array([15.0, 0.0]),
    np.array([0.0, -15.0]),
    np.array([0.0, 15.0]),
)


def build_actions_map(spec: TaskSpec) -> List[Dict[str, Any]]:
    """Enumerate the flat action list from the task's dict action interface
    (reference minerl.py:72-93): id 0 is the no-op; each binary key
    contributes one action ({key: 1}, with forward bundled for
    jump/sneak/sprint); the camera contributes four +/-15-degree rotations;
    each enum head contributes one action per non-noop value."""
    actions: List[Dict[str, Any]] = [{}]
    for head in spec.action_heads:
        if head.kind == "enum":
            for value in head.values[1:]:
                actions.append({head.key: value})
        elif head.kind == "camera":
            for delta in CAMERA_DELTAS:
                actions.append({head.key: delta})
        else:  # binary
            act: Dict[str, Any] = {head.key: 1}
            if head.key in ("jump", "sneak", "sprint"):
                act["forward"] = 1
            actions.append(act)
    return actions


def make_noop(spec: TaskSpec) -> Dict[str, Any]:
    noop: Dict[str, Any] = {}
    for head in spec.action_heads:
        if head.kind == "enum":
            noop[head.key] = head.values[0]
        elif head.kind == "camera":
            noop[head.key] = np.zeros(2, dtype=np.float32)
        else:
            noop[head.key] = 0
    return noop


class StickyActions:
    """Carries the sticky attack/jump counters across steps (reference
    minerl.py:123-136): attacking starts `sticky_attack` forced-attack steps
    (suppressing jump); jumping starts `sticky_jump` forced jump+forward
    steps."""

    def __init__(self, sticky_attack: int = 30, sticky_jump: int = 10):
        self.sticky_attack = sticky_attack
        self.sticky_jump = sticky_jump
        self.attack_counter = 0
        self.jump_counter = 0

    def reset(self) -> None:
        self.attack_counter = 0
        self.jump_counter = 0

    def apply(self, action: Dict[str, Any]) -> Dict[str, Any]:
        if self.sticky_attack:
            if action.get("attack"):
                self.attack_counter = self.sticky_attack
            if self.attack_counter > 0:
                action["attack"] = 1
                action["jump"] = 0
                self.attack_counter -= 1
        if self.sticky_jump:
            if action.get("jump"):
                self.jump_counter = self.sticky_jump
            if self.jump_counter > 0:
                action["jump"] = 1
                action["forward"] = 1
                self.jump_counter -= 1
        return action


class MineRLBackend:
    """Late-bound adapter over the real `minerl` package: compiles a
    `TaskSpec` into a herobraine EnvSpec (the handler construction mirrors
    the reference's CustomSimpleEmbodimentEnvSpec tree,
    minerl_envs/{backend,navigate,obtain}.py) and `.make()`s it. Tests
    substitute `FakeMineRLBackend` (minerl_mock.py)."""

    def __init__(self):
        from minerl.herobraine.hero import mc  # deferred: needs minerl + JDK

        self.all_items = list(mc.ALL_ITEMS)

    def make(
        self,
        spec: TaskSpec,
        resolution: Tuple[int, int] = (64, 64),
        break_speed: int = 100,
        seed: Optional[int] = None,
    ) -> Any:
        env_spec = self._compile(spec, resolution, break_speed)
        env = env_spec.make()
        if seed is not None and hasattr(env, "seed"):
            env.seed(seed)
        return env

    def _compile(self, spec: TaskSpec, resolution, break_speed):
        from abc import ABC

        from minerl.herobraine.env_spec import EnvSpec
        from minerl.herobraine.hero import handler, handlers
        from minerl.herobraine.hero.mc import INVERSE_KEYMAP, MS_PER_STEP

        class _BreakSpeed(handler.Handler):
            def __init__(self, multiplier):
                self.multiplier = multiplier

            def to_string(self):
                return f"break_speed({self.multiplier})"

            def xml_template(self):
                return "<BreakSpeedMultiplier>{{multiplier}}</BreakSpeedMultiplier>"

        task = spec  # captured

        class _CompiledSpec(EnvSpec, ABC):
            def __init__(self):
                super().__init__(task.name, max_episode_steps=task.max_episode_steps)

            def create_observables(self):
                obs = [
                    handlers.POVObservation(resolution),
                    handlers.ObservationFromCurrentLocation(),
                    handlers.ObservationFromLifeStats(),
                ]
                if task.inventory_items:
                    obs.append(
                        handlers.FlatInventoryObservation(list(task.inventory_items))
                    )
                if task.has_compass:
                    obs.append(handlers.CompassObservation(angle=True, distance=False))
                if task.has_equipment:
                    from minerl.herobraine.hero import mc

                    obs.append(
                        handlers.EquippedItemObservation(
                            items=mc.ALL_ITEMS, _default="air", _other="other"
                        )
                    )
                return obs

            def create_actionables(self):
                acts = [
                    handlers.KeybasedCommandAction(k, v)
                    for k, v in INVERSE_KEYMAP.items()
                    if any(h.key == k for h in task.action_heads)
                ] + [handlers.CameraAction()]
                enum_ctor = {
                    "place": handlers.PlaceBlock,
                    "equip": handlers.EquipAction,
                    "craft": handlers.CraftAction,
                    "nearbyCraft": handlers.CraftNearbyAction,
                    "nearbySmelt": handlers.SmeltItemNearby,
                }
                for head in task.extra_heads:
                    acts.append(
                        enum_ctor[head.key](
                            list(head.values), _other="none", _default="none"
                        )
                    )
                return acts

            def create_rewardables(self):
                rew = []
                if task.touch_block_rewards:
                    rew.append(
                        handlers.RewardForTouchingBlockType(
                            [
                                {"type": b, "behaviour": "onceOnly", "reward": r}
                                for b, r in task.touch_block_rewards
                            ]
                        )
                    )
                if task.compass_distance_reward:
                    rew.append(
                        handlers.RewardForDistanceTraveledToCompassTarget(
                            reward_per_block=task.compass_distance_reward
                        )
                    )
                if task.reward_schedule:
                    ctor = (
                        handlers.RewardForCollectingItems
                        if task.dense
                        else handlers.RewardForCollectingItemsOnce
                    )
                    rew.append(
                        ctor(
                            [
                                dict(type=r.item, amount=r.amount, reward=r.reward)
                                for r in task.reward_schedule
                            ]
                        )
                    )
                return rew

            def create_agent_start(self):
                start = [_BreakSpeed(break_speed)]
                if task.starting_inventory:
                    start.append(
                        handlers.SimpleInventoryAgentStart(
                            [
                                dict(type=item, quantity=str(qty))
                                for item, qty in task.starting_inventory
                            ]
                        )
                    )
                return start

            def create_agent_handlers(self):
                out = []
                if task.quit_on_touch_block:
                    out.append(
                        handlers.AgentQuitFromTouchingBlockType(
                            list(task.quit_on_touch_block)
                        )
                    )
                if task.quit_on_possess:
                    out.append(
                        handlers.AgentQuitFromPossessingItem(
                            [dict(type=i, amount=a) for i, a in task.quit_on_possess]
                        )
                    )
                if task.quit_on_craft:
                    out.append(
                        handlers.AgentQuitFromCraftingItem(
                            [dict(type=i, amount=a) for i, a in task.quit_on_craft]
                        )
                    )
                return out

            def create_server_world_generators(self):
                if task.world_generator.startswith("biome:"):
                    biome = int(task.world_generator.split(":")[1])
                    return [handlers.BiomeGenerator(biome=biome, force_reset=True)]
                return [handlers.DefaultWorldGenerator(force_reset=True)]

            def create_server_quit_producers(self):
                return [
                    handlers.ServerQuitFromTimeUp(
                        task.max_episode_steps * MS_PER_STEP
                    ),
                    handlers.ServerQuitWhenAnyAgentFinishes(),
                ]

            def create_server_decorators(self):
                if not task.navigation_decorator:
                    return []
                return [
                    handlers.NavigationDecorator(
                        max_randomized_radius=64,
                        min_randomized_radius=64,
                        block="diamond_block",
                        placement="surface",
                        max_radius=8,
                        min_radius=0,
                        max_randomized_distance=8,
                        min_randomized_distance=0,
                        randomize_compass_location=True,
                    )
                ]

            def create_server_initial_conditions(self):
                cond = [
                    handlers.TimeInitialCondition(
                        allow_passage_of_time=task.allow_time_passage,
                        start_time=task.start_time,
                    ),
                    handlers.SpawningInitialCondition(
                        "true" if task.allow_spawning else "false"
                    ),
                ]
                if task.weather:
                    cond.append(handlers.WeatherInitialCondition(task.weather))
                return cond

            def create_monitors(self):
                return []

            def is_from_folder(self, folder: str) -> bool:
                return False

            def get_docstring(self):
                return task.name

            def determine_success_from_rewards(self, rewards: list) -> bool:
                return task.determine_success(rewards)

        return _CompiledSpec()


class MineRLWrapper(gym.Env):
    """Gymnasium-facing MineRL env with dict observations and a flat
    Discrete action interface over the task's native dict actions."""

    metadata = {"render_modes": ["rgb_array"]}

    def __init__(
        self,
        task_id: str,
        height: int = 64,
        width: int = 64,
        pitch_limits: Tuple[int, int] = (-60, 60),
        seed: Optional[int] = None,
        sticky_attack: int = 30,
        sticky_jump: int = 10,
        break_speed_multiplier: int = 100,
        dense: bool = False,
        extreme: bool = False,
        backend: Optional[Any] = None,
        **kwargs: Any,
    ):
        key = task_id.lower()
        if key not in CUSTOM_TASKS:
            raise ValueError(
                f"unknown MineRL task {task_id!r}; expected one of "
                f"{sorted(CUSTOM_TASKS)}"
            )
        # navigate accepts extreme; obtain tasks ignore it (minerl.py:68-69)
        if key == "custom_navigate":
            self.spec_data: TaskSpec = CUSTOM_TASKS[key](dense=dense, extreme=extreme)
        else:
            self.spec_data = CUSTOM_TASKS[key](dense=dense)

        self._height = height
        self._width = width
        self._pitch_limits = pitch_limits
        self._backend = backend if backend is not None else MineRLBackend()
        self._items = ["_".join(i.split(" ")) for i in self._backend.all_items]
        self._item_id = {name: i for i, name in enumerate(self._items)}
        self.n_items = len(self._items)

        self._sim = self._backend.make(
            self.spec_data,
            resolution=(height, width),
            break_speed=break_speed_multiplier,
            seed=seed,
        )
        self._sticky = StickyActions(sticky_attack, sticky_jump)
        self._noop = make_noop(self.spec_data)
        self.actions_map = build_actions_map(self.spec_data)
        self._max_inventory = np.zeros(self.n_items, dtype=np.float32)
        self._pos = {"pitch": 0.0, "yaw": 0.0}

        self.action_space = gym.spaces.Discrete(len(self.actions_map))
        obs_space: Dict[str, gym.spaces.Space] = {
            "rgb": gym.spaces.Box(0, 255, (height, width, 3), np.uint8),
            "life_stats": gym.spaces.Box(
                0.0, np.array([20.0, 20.0, 300.0]), (3,), np.float32
            ),
            "inventory": gym.spaces.Box(0.0, np.inf, (self.n_items,), np.float32),
            "max_inventory": gym.spaces.Box(0.0, np.inf, (self.n_items,), np.float32),
        }
        if self.spec_data.has_compass:
            obs_space["compass"] = gym.spaces.Box(-180.0, 180.0, (1,), np.float32)
        if self.spec_data.has_equipment:
            obs_space["equipment"] = gym.spaces.Box(0.0, 1.0, (self.n_items,), np.int32)
        self.observation_space = gym.spaces.Dict(obs_space)
        self.render_mode = "rgb_array"
        self.observation_space.seed(seed)
        self.action_space.seed(seed)

    # ---- conversions ---------------------------------------------------------

    def _convert_action(self, action: Any) -> Dict[str, Any]:
        converted = copy.deepcopy(self._noop)
        converted.update(self.actions_map[int(np.asarray(action).item())])
        return self._sticky.apply(converted)

    def _convert_inventory(self, inventory: Dict[str, Any]) -> np.ndarray:
        counts = np.zeros(self.n_items, dtype=np.float32)
        for item, quantity in inventory.items():
            item_id = self._item_id["_".join(item.split(" "))]
            # reference quirk kept: "air" counts one per entry (minerl.py:149)
            counts[item_id] += 1.0 if item == "air" else float(quantity)
        self._max_inventory = np.maximum(counts, self._max_inventory)
        return counts

    def _convert_equipment(self, equipment: Dict[str, Any]) -> np.ndarray:
        one_hot = np.zeros(self.n_items, dtype=np.int32)
        name = "_".join(str(equipment["mainhand"]["type"]).split(" "))
        if name in self._item_id:
            one_hot[self._item_id[name]] = 1
        return one_hot

    def _convert_obs(self, obs: Dict[str, Any]) -> Dict[str, np.ndarray]:
        converted = {
            "rgb": np.asarray(obs["pov"], dtype=np.uint8).copy(),
            "life_stats": np.array(
                [
                    np.asarray(obs["life_stats"]["life"]).item(),
                    np.asarray(obs["life_stats"]["food"]).item(),
                    np.asarray(obs["life_stats"]["air"]).item(),
                ],
                dtype=np.float32,
            ),
            "inventory": self._convert_inventory(obs["inventory"]),
        }
        converted["max_inventory"] = self._max_inventory.copy()
        if self.spec_data.has_equipment:
            converted["equipment"] = self._convert_equipment(obs["equipped_items"])
        if self.spec_data.has_compass:
            converted["compass"] = np.asarray(
                obs["compass"]["angle"], dtype=np.float32
            ).reshape(-1)
        return converted

    # ---- gym API -------------------------------------------------------------

    def step(self, action: Any):
        converted = self._convert_action(action)
        camera = np.asarray(converted["camera"], dtype=np.float32)
        next_pitch = self._pos["pitch"] + float(camera[0])
        next_yaw = ((self._pos["yaw"] + float(camera[1])) + 180.0) % 360.0 - 180.0
        if not (self._pitch_limits[0] <= next_pitch <= self._pitch_limits[1]):
            converted["camera"] = np.array([0.0, float(camera[1])], dtype=np.float32)
            next_pitch = self._pos["pitch"]

        obs, reward, done, _ = self._sim.step(converted)
        self._pos = {"pitch": next_pitch, "yaw": next_yaw}
        return self._convert_obs(obs), reward, done, False, {}

    def reset(self, seed: Optional[int] = None, options: Optional[dict] = None):
        obs = self._sim.reset()
        self._max_inventory = np.zeros(self.n_items, dtype=np.float32)
        self._sticky.reset()
        self._pos = {"pitch": 0.0, "yaw": 0.0}
        return self._convert_obs(obs), {}

    def render(self):
        return None

    def close(self):
        self._sim.close()
        return super().close()
