"""MineRL 0.4.4 wrapper (capability target:
/root/reference/sheeprl/envs/minerl.py + envs/minerl_envs/ — custom
navigate/obtain task backends, sticky attack/jump, pitch limits). The
`minerl` package is not present in this image; the wrapper raises an
actionable error until the backend is installed."""

from __future__ import annotations

try:
    import minerl  # noqa: F401

    _MINERL_AVAILABLE = True
except ImportError:
    _MINERL_AVAILABLE = False


class MineRLWrapper:
    def __init__(self, *args, **kwargs):
        if not _MINERL_AVAILABLE:
            raise ModuleNotFoundError(
                "minerl is not installed: `pip install minerl==0.4.4` "
                "(requires JDK 8); env ids look like `minerl_custom_navigate`"
            )
        raise NotImplementedError(
            "MineRL wrapper pending implementation against an installed "
            "minerl backend (reference: sheeprl/envs/minerl.py)"
        )
