"""Scripted stand-in for the DIAMBRA Arena engine.

Same philosophy as `minedojo_mock.py`/`minerl_mock.py`: the real engine is a
licensed docker container, so CI drives `DiambraWrapper` through a fake that
mimics the engine's interface — old-gym 4-tuple step API, a dict observation
space mixing image frames, Box vectors, and Discrete scalars, and
discrete/multidiscrete action spaces — while recording the settings/wrappers
dicts and `rank` passed to `make` for assertions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class _Discrete:
    def __init__(self, n: int):
        self.n = n


class _MultiDiscrete:
    def __init__(self, nvec):
        self.nvec = np.asarray(nvec)


class _Box:
    def __init__(self, low, high, shape, dtype):
        self.low, self.high, self.shape, self.dtype = low, high, shape, dtype


class _DictSpace:
    def __init__(self, spaces: Dict[str, Any]):
        self.spaces = spaces


class FakeDiambraEngine:
    """Deterministic fake engine: fixed frames, oscillating health bars,
    episodes end after `episode_length` steps."""

    def __init__(
        self,
        env_id: str,
        settings: dict,
        wrappers: dict,
        seed,
        rank: int,
        episode_length: int = 8,
    ):
        self.env_id = env_id
        self.settings = settings
        self.wrappers = wrappers
        self.seed = seed
        self.rank = rank
        self._episode_length = episode_length
        self._t = 0
        self.received_actions: list = []

        h, w, gray = settings["frame_shape"]
        channels = 1 if gray else 3
        self._frame_shape = (h, w, channels)
        if settings["action_space"] == "discrete":
            self.action_space: Any = _Discrete(10)
        else:
            self.action_space = _MultiDiscrete([9, 8])
        self.observation_space = _DictSpace(
            {
                "frame": _Box(0, 255, self._frame_shape, np.uint8),
                "ownHealth": _Box(0.0, 1.0, (1,), np.float32),
                "oppHealth": _Box(0.0, 1.0, (1,), np.float32),
                "stage": _Discrete(3),
                "ownSide": _Discrete(2),
            }
        )

    def _obs(self) -> Dict[str, Any]:
        return {
            "frame": np.full(self._frame_shape, self._t % 255, dtype=np.uint8),
            "ownHealth": np.array([1.0 - 0.1 * self._t], dtype=np.float32),
            "oppHealth": np.array([1.0 - 0.05 * self._t], dtype=np.float32),
            "stage": 1,  # engine emits Discrete obs as bare ints
            "ownSide": self.rank % 2,
        }

    def reset(self) -> Dict[str, Any]:
        self._t = 0
        return self._obs()

    def step(self, action):
        self.received_actions.append(np.asarray(action).copy())
        self._t += 1
        done = self._t >= self._episode_length
        return self._obs(), (1.0 if done else 0.1), done, {}

    def close(self) -> None:
        pass


class FakeDiambraBackend:
    """Backend object compatible with DiambraWrapper(backend=...)."""

    def __init__(self, episode_length: int = 8):
        self._episode_length = episode_length
        self.last_engine: Optional[FakeDiambraEngine] = None

    def make(self, env_id: str, settings: dict, wrappers: dict, seed, rank: int):
        self.last_engine = FakeDiambraEngine(
            env_id, settings, wrappers, seed, rank, self._episode_length
        )
        return self.last_engine
