"""Scripted stand-in for the MineDojo simulator.

The reference ships deterministic dummy envs in the package as its CI backend
(/root/reference/sheeprl/envs/dummy.py); this extends that philosophy to
MineDojo, whose real backend needs a JDK + Minecraft. The mock emits
observations in the exact nested format the real sim produces (inventory
name/quantity tables, delta_inv, equipment, life_stats, masks, location
stats), accepts native 8-dim actions, and records them for assertions — so
`MineDojoWrapper`'s full action/observation mapping runs in CI unmodified.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

# tiny vocabulary; "wooden pickaxe" keeps a space to exercise the
# space->underscore canonicalization the real item names need
MOCK_ITEMS = ["air", "stone", "dirt", "wooden pickaxe", "apple"]
MOCK_CRAFT_ITEMS = ["stick", "torch", "planks"]


class FakeMineDojoSim:
    """Deterministic sim: fixed inventory, fixed life stats, a wandering
    pitch that increases with every pitch-up action, episodes end after
    `episode_length` steps."""

    def __init__(
        self,
        image_size=(64, 64),
        episode_length: int = 16,
        inventory: Optional[Sequence[tuple]] = None,
        **kwargs: Any,
    ):
        self._h, self._w = image_size
        self._episode_length = episode_length
        self._t = 0
        self._pitch = 0.0
        self._yaw = 0.0
        # (name, quantity, can_equip, can_destroy) per inventory slot
        self._inventory = list(
            inventory
            if inventory is not None
            else [
                ("air", 1, False, False),
                ("stone", 3, False, True),
                ("wooden pickaxe", 1, True, True),
                ("stone", 2, False, True),
            ]
        )
        self.received_actions: list = []
        self.observation_space = {
            "rgb": type("Box", (), {"shape": (3, self._h, self._w)})()
        }

    def _obs(self) -> Dict[str, Any]:
        names = np.array([n for n, *_ in self._inventory], dtype=object)
        quantities = np.array([q for _, q, *_ in self._inventory], dtype=np.int64)
        return {
            "rgb": np.full((3, self._h, self._w), self._t % 255, dtype=np.uint8),
            "inventory": {"name": names, "quantity": quantities},
            "delta_inv": {
                "inc_name_by_craft": np.array(["stone"], dtype=object),
                "inc_quantity_by_craft": np.array([1]),
                "dec_name_by_craft": np.array([], dtype=object),
                "dec_quantity_by_craft": np.array([]),
                "inc_name_by_other": np.array([], dtype=object),
                "inc_quantity_by_other": np.array([]),
                "dec_name_by_other": np.array(["apple"], dtype=object),
                "dec_quantity_by_other": np.array([1]),
            },
            "equipment": {"name": np.array(["wooden pickaxe"], dtype=object)},
            "life_stats": {
                "life": np.array([20.0]),
                "food": np.array([20.0]),
                "oxygen": np.array([300.0]),
            },
            "masks": {
                # functional: noop/use/drop/attack/craft allowed; equip/place/
                # destroy allowed (gated by inventory masks in the wrapper)
                "action_type": np.ones(8, dtype=bool),
                "equip": np.array(
                    [e for _, _, e, _ in self._inventory], dtype=bool
                ),
                "destroy": np.array(
                    [d for _, _, _, d in self._inventory], dtype=bool
                ),
                "craft_smelt": np.array(
                    [True] * (len(MOCK_CRAFT_ITEMS) - 1) + [False]
                ),
            },
            "location_stats": {
                "pos": np.array([0.5, 64.0, -0.5]),
                "pitch": np.array([self._pitch]),
                "yaw": np.array([self._yaw]),
                "biome_id": np.array([7]),
            },
        }

    def reset(self) -> Dict[str, Any]:
        self._t = 0
        self._pitch = 0.0
        self._yaw = 0.0
        return self._obs()

    def step(self, action):
        action = np.asarray(action)
        self.received_actions.append(action.copy())
        self._t += 1
        self._pitch += float(action[3] - 12) * 15.0
        self._yaw += float(action[4] - 12) * 15.0
        done = self._t >= self._episode_length
        reward = 1.0 if done else 0.0
        return self._obs(), reward, done, {}

    def close(self) -> None:
        pass


class FakeMineDojoBackend:
    """Backend object compatible with MineDojoWrapper(backend=...)."""

    def __init__(self, episode_length: int = 16, inventory=None):
        self.all_items = ["_".join(i.split(" ")) for i in MOCK_ITEMS]
        self.craft_smelt_items = list(MOCK_CRAFT_ITEMS)
        self._episode_length = episode_length
        self._inventory = inventory
        self.last_sim: Optional[FakeMineDojoSim] = None
        self.last_make_kwargs: Dict[str, Any] = {}

    def make(self, task_id: str, **kwargs: Any) -> FakeMineDojoSim:
        self.last_make_kwargs = dict(kwargs, task_id=task_id)
        self.last_sim = FakeMineDojoSim(
            image_size=kwargs.get("image_size", (64, 64)),
            episode_length=self._episode_length,
            inventory=self._inventory,
        )
        return self.last_sim
