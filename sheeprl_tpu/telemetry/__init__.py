"""Runtime telemetry subsystem (ISSUE 2): always-on phase timers, XLA
recompile/memory tracking, a NaN/inf watchdog, and a rank-0 structured JSONL
event log with console heartbeat — shared by every algorithm main. See
howto/observability.md for the schema and `tools/telemetry_report.py` for
offline analysis of a finished or crashed run."""

from .compile_tracker import CompileTracker, monitoring_supported
from .core import Telemetry, active_telemetry, device_memory_gauges, emit
from .events import JsonlEventLog
from .phase import PhaseTimers
from .trace import (
    ClockSync,
    ProfileWindow,
    Span,
    Tracer,
    ensure_run_id,
    handle_profile_frame,
    install_profile_signal,
    new_span_id,
    profile_window,
    trace_enabled,
)

__all__ = [
    "ClockSync",
    "CompileTracker",
    "JsonlEventLog",
    "PhaseTimers",
    "ProfileWindow",
    "Span",
    "Telemetry",
    "Tracer",
    "active_telemetry",
    "device_memory_gauges",
    "emit",
    "ensure_run_id",
    "handle_profile_frame",
    "install_profile_signal",
    "monitoring_supported",
    "new_span_id",
    "profile_window",
    "trace_enabled",
]
