"""Runtime telemetry subsystem (ISSUE 2): always-on phase timers, XLA
recompile/memory tracking, a NaN/inf watchdog, and a rank-0 structured JSONL
event log with console heartbeat — shared by every algorithm main. See
howto/observability.md for the schema and `tools/telemetry_report.py` for
offline analysis of a finished or crashed run."""

from .compile_tracker import CompileTracker, monitoring_supported
from .core import Telemetry, active_telemetry, device_memory_gauges, emit
from .events import JsonlEventLog
from .phase import PhaseTimers

__all__ = [
    "CompileTracker",
    "JsonlEventLog",
    "PhaseTimers",
    "Telemetry",
    "active_telemetry",
    "device_memory_gauges",
    "emit",
    "monitoring_supported",
]
