"""Rank-0 structured JSONL event log (`<log_dir>/telemetry.jsonl`).

One self-describing line per event — run lifecycle (`start`, `checkpoint`,
`profile.start/stop`, `end`, `crash`), every logged metric dict (`log`), and
health findings (`health.nan`) — so a finished OR crashed run can be
reconstructed offline by `tools/telemetry_report.py` without TensorBoard.
Schema (stable, consumed by the report tool and tests):

    {"ts": <unix seconds>, "event": "<name>", ...event payload}
    {"ts": ..., "event": "log", "step": 123, "metrics": {"Loss/x": 0.1, ...}}

Writes are a single `write()` of one line + flush: atomic enough for a
line-oriented append-only file on POSIX, and a crash mid-run loses at most
the event being written. High-rate trace events (`span`, `trace.clock` —
sheepscope emits a few per learner update) are the one exception: they
flush lazily (at most every 0.25s, and on the next lifecycle event or
close), so a hard kill loses at most a quarter-second of spans — a tail
`tools/sheeptrace.py` already tolerates. Non-rank-0 processes construct
the writer disabled (path=None) — same rank-0-only policy as
TensorBoardLogger.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any

__all__ = ["JsonlEventLog"]


def _jsonable(value: Any):
    """Best-effort scalarization: metric dicts carry floats/ints/strings;
    device scalars and numpy types get float()'d, non-finite floats become
    strings (json.dumps would otherwise emit bare NaN/Infinity tokens that
    strict parsers — including the replay path — reject)."""
    if isinstance(value, bool) or value is None or isinstance(value, (int, str)):
        return value
    if isinstance(value, float):
        return value if math.isfinite(value) else repr(value)
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    try:
        return _jsonable(float(value))
    except Exception:
        return repr(value)


# events that may flush lazily (see module docstring)
_LAZY_FLUSH_EVENTS = frozenset({"span", "trace.clock"})
_LAZY_FLUSH_S = 0.25


class JsonlEventLog:
    def __init__(self, path: str | None):
        self.path = path
        self._fh = None
        self._last_flush = 0.0
        if path is not None:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def emit(self, event: str, **data: Any) -> None:
        if self._fh is None:
            return
        record = {"ts": round(time.time(), 3), "event": event}
        record.update(data)
        try:
            try:
                # fast path: span-rate payloads are plain ints/floats/strs;
                # allow_nan=False turns a bare NaN/Infinity token into the
                # ValueError that routes it through _jsonable below
                line = json.dumps(record, allow_nan=False)
            except (TypeError, ValueError):
                record = {"ts": record["ts"], "event": event}
                record.update({k: _jsonable(v) for k, v in data.items()})
                line = json.dumps(record)
            self._fh.write(line + "\n")
            if event in _LAZY_FLUSH_EVENTS:
                now = time.monotonic()
                if now - self._last_flush < _LAZY_FLUSH_S:
                    return
            self._fh.flush()
            self._last_flush = time.monotonic()
        except (OSError, ValueError):
            # a full disk or a closed fd must never kill the training loop
            pass

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
