"""The run-wide telemetry orchestrator every algorithm main constructs.

Always-on, low-overhead observability (ISSUE 2 tentpole): hierarchical phase
timers, an XLA recompile tracker, device-memory gauges, a NaN/inf watchdog
over the logged metrics, and a rank-0 JSONL event log with a periodic
one-line console heartbeat. A main wires it in ~3 calls:

    telem = Telemetry.from_args(args, log_dir, rank, algo="ppo")
    ...
    telem.mark("rollout")            # or: with telem.phase("rollout"): ...
    ...
    logger.log_dict(telem.interval(aggregator.compute(), global_step, sps), step)
    ...
    telem.close()

`interval()` merges everything the subsystem measured since the last call
into the metric dict (so the phase/compile/memory series ride the existing
TensorBoard pipeline with no extra logger calls), appends the merged dict to
`<log_dir>/telemetry.jsonl`, runs the non-finite watchdog, and prints the
heartbeat when due. Everything is host-side bookkeeping — no device syncs,
no jit retraces — so the instrumented hot loop stays within noise of the
uninstrumented one (bench.py --telemetry A/B + the overhead smoke test are
the receipts).

Kill switch: SHEEPRL_TPU_TELEMETRY=0 disables the subsystem (interval()
passes metrics through untouched); non-rank-0 processes keep the timers (the
merged dict goes to their no-op logger anyway) but never write JSONL or
heartbeat lines.
"""

from __future__ import annotations

import atexit
import math
import os
import sys
import time
import traceback
from typing import Any, Callable, Iterator

from .compile_tracker import CompileTracker
from .events import JsonlEventLog
from .phase import PhaseTimers

__all__ = ["Telemetry", "emit", "active_telemetry", "device_memory_gauges"]

# ---------------------------------------------------------------------------
# Global emit: shared helpers that should not depend on a Telemetry handle
# (save_checkpoint, StepProfiler) publish lifecycle events through here; they
# reach every live instance (normally exactly one per process).
# ---------------------------------------------------------------------------

_active: list["Telemetry"] = []


def active_telemetry() -> list["Telemetry"]:
    return list(_active)


def emit(event: str, **data: Any) -> None:
    """Publish a lifecycle event to every active Telemetry instance; no-op
    when none is live (tools, tests, bare library use)."""
    for t in list(_active):
        t.event(event, **data)


# last uncaught exception, captured so the atexit crash event can name it
_last_exc: list[str] = []
_excepthook_installed = False


def _install_excepthook() -> None:
    global _excepthook_installed
    if _excepthook_installed:
        return
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        _last_exc[:] = ["".join(traceback.format_exception_only(exc_type, exc)).strip()]
        prev(exc_type, exc, tb)

    sys.excepthook = hook
    _excepthook_installed = True


def device_memory_gauges() -> dict[str, float]:
    """Per-local-device HBM gauges from `device.memory_stats()`:
    bytes_in_use + peak_bytes_in_use (CPU devices report none — empty dict)."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return {}
    out: dict[str, float] = {}
    for i, d in enumerate(devices):
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        for src, dst in (
            ("bytes_in_use", f"Memory/d{i}_bytes_in_use"),
            ("peak_bytes_in_use", f"Memory/d{i}_peak_bytes_in_use"),
        ):
            if src in stats:
                out[dst] = float(stats[src])
    return out


class Telemetry:
    FILENAME = "telemetry.jsonl"

    def __init__(
        self,
        log_dir: str | None,
        rank: int = 0,
        algo: str = "",
        enabled: bool = True,
        heartbeat_s: float = 30.0,
        role: str = "",
        run_id: str | None = None,
    ):
        self.enabled = enabled
        self.rank = rank
        self.algo = algo
        # sheepscope role shard (ISSUE 17): non-learner roles write
        # telemetry.<role>.jsonl next to the learner's telemetry.jsonl so
        # tools/sheeptrace.py can merge all of a run's shards by run id
        self.role = role or "learner"
        self.run_id = run_id
        self.log_dir = log_dir
        self.heartbeat_s = heartbeat_s
        self.timers = PhaseTimers()
        self._gauge_sources: list[Callable[[], dict[str, float]]] = []
        self._last_step: int | None = None
        self._last_heartbeat = time.monotonic()
        self._last_jsonl_log = 0.0
        self._last_nan_warn = 0.0
        self._closed = not enabled
        self._compiles = CompileTracker()
        self._tracer = None
        write_jsonl = enabled and rank == 0 and log_dir is not None
        filename = (
            self.FILENAME
            if self.role == "learner"
            else f"telemetry.{self.role}.jsonl"
        )
        self._log = JsonlEventLog(
            os.path.join(log_dir, filename) if write_jsonl else None
        )
        if enabled:
            self._compiles.attach()
            _install_excepthook()
            atexit.register(self._atexit)
            _active.append(self)

    @property
    def tracer(self):
        """This shard's span emitter (lazy — trace.py is pure stdlib but
        there is no reason to build a Tracer nobody asks for)."""
        if self._tracer is None:
            from .trace import Tracer

            self._tracer = Tracer(self)
        return self._tracer

    # ---- construction policy ---------------------------------------------
    @classmethod
    def from_args(
        cls, args: Any, log_dir: str, rank: int = 0, algo: str = "", role: str = ""
    ) -> "Telemetry":
        """The mains' shared construction helper: always-on unless
        SHEEPRL_TPU_TELEMETRY=0, JSONL/heartbeat on process 0 only, and a
        `start` lifecycle event carrying the run identity. Checkpoint and
        profile-window lifecycle events arrive via the module-level `emit`
        (save_checkpoint / StepProfiler publish them directly). `role`
        selects the sheepscope shard filename (actor{N}/serve) and stamps
        the shared run id into the `start` event."""
        from .trace import ensure_run_id

        # sheepsync (ISSUE 18): the runtime thread sanitizer is installed
        # as early as possible so locks allocated by this process are
        # instrumented; its Sync/* gauges ride every telemetry interval
        from ..analysis import thread_sanitizer

        if getattr(args, "sanitize_threads", False):
            thread_sanitizer.install()
        else:
            thread_sanitizer.maybe_install_from_env()

        enabled = os.environ.get("SHEEPRL_TPU_TELEMETRY", "1") != "0"
        telem = cls(
            log_dir, rank=rank, algo=algo, enabled=enabled,
            role=role, run_id=ensure_run_id() if enabled else None,
        )
        if enabled:
            try:
                import jax

                backend = jax.default_backend()
                n_local = len(jax.local_devices())
            except Exception:
                backend, n_local = "unknown", 0
            telem.event(
                "start",
                algo=algo,
                env_id=getattr(args, "env_id", None),
                seed=getattr(args, "seed", None),
                num_envs=getattr(args, "num_envs", None),
                precision=getattr(args, "precision", None),
                backend=backend,
                local_devices=n_local,
                rank=rank,
                log_dir=log_dir,
                role=telem.role,
                run=telem.run_id,
                compile_tracking=telem._compiles.supported,
            )
        san = thread_sanitizer.installed()
        if san is not None:
            telem.add_gauges(thread_sanitizer.gauges)
            # install() ran before this instance existed, so its start
            # marker found no sink — re-emit through the live instance
            telem.event(
                "sync.sanitizer_start",
                committed_edges=len(san.committed),
                lock_sites=len(san.sites),
            )
        return telem

    # ---- phase timing -----------------------------------------------------
    def phase(self, name: str) -> Iterator[None]:
        return self.timers.phase(name)

    def mark(self, name: str | None) -> None:
        if self.enabled:
            self.timers.mark(name)

    # ---- gauges / events --------------------------------------------------
    def add_gauges(self, source: Callable[[], dict[str, float]]) -> None:
        """Register a callable polled at every interval (e.g. the decoupled
        topology's queue-depth/staleness gauges)."""
        self._gauge_sources.append(source)

    def event(self, name: str, /, **data: Any) -> None:
        # positional-only: span events carry their own `name` payload key
        self._log.emit(name, **data)

    # ---- the per-logging-interval merge ----------------------------------
    def interval(
        self, metrics: dict[str, Any], step: int, sps: float | None = None
    ) -> dict[str, Any]:
        """Merge this interval's telemetry into `metrics` (returned as a new
        dict), append the JSONL `log` event, run the NaN watchdog, and print
        the heartbeat when due. Call once per logging interval, BEFORE
        `logger.log_dict`."""
        if not self.enabled:
            return metrics
        out = dict(metrics)
        dstep = None if self._last_step is None else step - self._last_step
        for name, secs in self.timers.flush().items():
            out[f"Time/{name}_seconds"] = secs
            if dstep and secs > 0.0:
                out[f"Time/{name}_sps"] = dstep / secs
        if self._compiles.supported:
            comp = self._compiles.flush()
            out["XLA/recompiles"] = comp["compiles"]
            out["XLA/compile_seconds"] = comp["compile_seconds"]
            out["XLA/total_compiles"] = comp["total_compiles"]
            out["XLA/total_compile_seconds"] = comp["total_compile_seconds"]
        out.update(device_memory_gauges())
        gauge_errors = 0
        for source in self._gauge_sources:
            try:
                out.update(source())
            except Exception:
                # a gauge source must never kill the loop — but a silently
                # dead source is an observability hole (SL012), so the
                # failure count rides the metrics it failed to produce
                gauge_errors += 1
        if gauge_errors:
            out["Health/gauge_source_errors"] = float(gauge_errors)
        self._nan_watchdog(out, step)
        self._last_step = step
        now = time.monotonic()
        # JSONL: every interval that carries real metrics, throttled to the
        # heartbeat cadence for metric-less intervals (the dreamer family
        # calls interval() every env step; most carry only phase time)
        if metrics or (now - self._last_jsonl_log) >= self.heartbeat_s:
            payload = dict(out)
            if sps is not None:
                payload["Time/step_per_second"] = sps
            self.event("log", step=step, metrics=payload)
            self._last_jsonl_log = now
        if self.rank == 0 and (now - self._last_heartbeat) >= self.heartbeat_s:
            self._heartbeat(out, step, sps)
            self._last_heartbeat = now
        return out

    # ---- internals --------------------------------------------------------
    def _nan_watchdog(self, merged: dict[str, Any], step: int) -> None:
        bad = {}
        for k, v in merged.items():
            if isinstance(v, float) and not math.isfinite(v):
                bad[k] = repr(v)
        if not bad:
            return
        merged["Health/nonfinite_metrics"] = float(len(bad))
        self.event("health.nan", step=step, keys=sorted(bad), values=bad)
        now = time.monotonic()
        if self.rank == 0 and now - self._last_nan_warn >= self.heartbeat_s:
            print(
                f"[telemetry {self.algo}] WARNING: non-finite metrics at "
                f"step {step}: {sorted(bad)}",
                file=sys.stderr,
            )
            self._last_nan_warn = now

    def _heartbeat(self, merged: dict[str, Any], step: int, sps: float | None) -> None:
        phases = {
            k[len("Time/"):-len("_seconds")]: v
            for k, v in merged.items()
            if k.startswith("Time/") and k.endswith("_seconds")
        }
        total = sum(phases.values())
        if total > 0:
            top = sorted(phases.items(), key=lambda kv: -kv[1])[:4]
            breakdown = " ".join(f"{n} {100 * s / total:.0f}%" for n, s in top)
        else:
            breakdown = "-"
        bits = [f"[telemetry {self.algo}] step={step}"]
        if sps is not None:
            bits.append(f"sps={sps:.1f}")
        bits.append(f"| {breakdown}")
        if "XLA/total_compiles" in merged:
            bits.append(
                f"| compiles={merged['XLA/total_compiles']:.0f} "
                f"({merged['XLA/total_compile_seconds']:.1f}s)"
            )
        mem = [v for k, v in merged.items() if k.endswith("_bytes_in_use")]
        if mem:
            bits.append(f"| mem={sum(mem) / 2**30:.2f}GiB")
        print(" ".join(bits), file=sys.stderr)

    # ---- lifecycle --------------------------------------------------------
    def _atexit(self) -> None:
        if not self._closed:
            self.event(
                "crash",
                error=_last_exc[0] if _last_exc else "process exited without close()",
            )
            self._teardown()

    def abort(self, error: str | None = None) -> None:
        """Crash-path teardown (the `@resilience.crashsafe` scope): emit a
        `crash` record when given one, then close the JSONL WITHOUT the
        clean-exit `end` event — a post-mortem can tell an aborted run from
        a completed one by the missing `end`."""
        if self._closed:
            return
        if error is not None:
            self.event("crash", error=error, handled=True)
        try:
            atexit.unregister(self._atexit)
        # sheeplint: disable=SL012 — unregister during interpreter teardown;
        # the event log this would be reported to is being closed right here
        except Exception:
            pass
        self._teardown()

    def close(self) -> None:
        """Normal end-of-run teardown: flush open phases, emit `end`."""
        if self._closed:
            return
        self.event("end", phases=self.timers.flush())
        try:
            atexit.unregister(self._atexit)
        # sheeplint: disable=SL012 — unregister during interpreter teardown;
        # the event log this would be reported to is being closed right here
        except Exception:
            pass
        self._teardown()

    def _teardown(self) -> None:
        self._closed = True
        self._compiles.detach()
        self._log.close()
        if self in _active:
            _active.remove(self)
