"""Hierarchical phase timers — the always-on answer to "where did the step
go?" that the reference's single `Time/step_per_second` scalar cannot give
(it has ONE wall-clock ratio, reference ppo.py:372; a slow run is opaque).

Two usage styles over one accumulator:

  - `with timers.phase("train"):` — nestable context manager; nested phases
    get hierarchical names (`train/dispatch`), time is attributed to BOTH
    the child and its parent (the parent's span covers the child). Exception
    safe: the time up to the raise is still recorded.
  - `timers.mark("rollout")` — linear sectioning for the mains' top-level
    loops, where wrapping a 60-line hot loop in a `with` block would force a
    re-indent of the whole body: each mark ends the previous marked section
    and opens the named one; `mark(None)` just ends.

`flush()` returns the accumulated seconds per phase since the last flush and
restarts any phase that is still open (an open phase contributes its elapsed
time to the flushed interval and keeps running), so per-interval sums never
lose or double-count time across logging intervals.

Overhead: one `perf_counter()` call and a dict add per transition — tens of
nanoseconds to ~1us, invisible next to an env step or a jit dispatch.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Iterator

__all__ = ["PhaseTimers"]


class PhaseTimers:
    def __init__(self) -> None:
        self._acc: dict[str, float] = {}
        # context-manager nesting stack: (full_name, start_time)
        self._stack: list[tuple[str, float]] = []
        # linear mark() section: (name, start_time) or None
        self._mark: tuple[str, float] | None = None

    # ---- context-manager style -------------------------------------------
    @contextmanager
    def phase(self, name: str) -> Iterator[None]:
        full = f"{self._stack[-1][0]}/{name}" if self._stack else name
        self._stack.append((full, time.perf_counter()))
        try:
            yield
        finally:
            fname, t0 = self._stack.pop()
            self._acc[fname] = self._acc.get(fname, 0.0) + (time.perf_counter() - t0)

    # ---- linear sectioning ------------------------------------------------
    def mark(self, name: str | None) -> None:
        """End the current marked section (if any) and open `name`."""
        now = time.perf_counter()
        if self._mark is not None:
            prev, t0 = self._mark
            self._acc[prev] = self._acc.get(prev, 0.0) + (now - t0)
        self._mark = (name, now) if name is not None else None

    # ---- interval flush ---------------------------------------------------
    def flush(self) -> dict[str, float]:
        """Accumulated seconds per phase since the last flush. Open phases
        (mark sections or live context managers) contribute their elapsed
        time and restart at now."""
        now = time.perf_counter()
        out = dict(self._acc)
        self._acc.clear()
        if self._mark is not None:
            name, t0 = self._mark
            out[name] = out.get(name, 0.0) + (now - t0)
            self._mark = (name, now)
        for i, (name, t0) in enumerate(self._stack):
            out[name] = out.get(name, 0.0) + (now - t0)
            self._stack[i] = (name, now)
        return out
