"""sheepscope: the cross-process distributed tracing plane (ISSUE 17).

The repo runs three cooperating tiers — the learner, flock actor
processes, and the sheepserve server — and until this module only the
learner's rank-0 `telemetry.jsonl` existed. sheepscope adds:

  1. **Per-role telemetry shards.** Every process gets a real
     `Telemetry` instance writing `telemetry.<role>.jsonl` (role =
     ``actor{N}`` / ``serve``; the learner keeps the bare
     ``telemetry.jsonl`` name for backwards compatibility). Shards are
     keyed by a shared run id (`ensure_run_id`, exported through
     ``SHEEPRL_TPU_TRACE_RUN`` so subprocesses inherit it).

  2. **Spans.** A span is one JSONL event (``"event": "span"``) with a
     compact random id, an optional parent id, and wall-clock ``t0``/
     ``t1``. Parent ids cross process boundaries by riding FLK1 frame
     meta (PUSH / WEIGHTS / REQUEST / RESPONSE), giving end-to-end
     provenance actor-collect -> push -> ingest -> drain -> train ->
     publish -> served-response. `Tracer` is the per-shard emitter;
     `tools/sheeptrace.py` merges shards and reconstructs the chains.

  3. **Clock offsets.** Shards are written with each host's own wall
     clock. `ClockSync` piggybacks an NTP-style estimate on the existing
     HEARTBEAT exchange (actor sends its wall time, the service replies
     with its own): ``offset = server_wall - (t0 + t1) / 2`` with the
     minimum-RTT sample winning. The estimate is recorded as a
     ``trace.clock`` event in the actor's shard so the merge tool can
     map every shard onto the learner's timeline.

  4. **On-demand profiling.** `ProfileWindow` opens a bounded
     `jax.profiler.trace` window on a live process — triggered either by
     a PROFILE frame (`flock/wire.py` kind 17, handled by the flock
     service and the serve server) or by SIGUSR2
     (`install_profile_signal`). The artifact path is recorded as a
     ``profile.window.start``/``profile.window.stop`` telemetry event.

Kill switch: ``SHEEPRL_TPU_TRACE=0`` disables span/clock emission (the
wire fields simply stay absent; old peers never see a difference).
Span emission is per-chunk / per-update / per-request — never per env
step — so the trace plane stays within the bench A/B overhead budget.
"""

from __future__ import annotations

import os
import random
import secrets
import signal
import threading
import time
from typing import Any

__all__ = [
    "ClockSync",
    "ProfileWindow",
    "RUN_ENV",
    "Span",
    "TRACE_ENV",
    "Tracer",
    "ensure_run_id",
    "handle_profile_frame",
    "install_profile_signal",
    "new_run_id",
    "new_span_id",
    "profile_window",
    "trace_enabled",
]

TRACE_ENV = "SHEEPRL_TPU_TRACE"
RUN_ENV = "SHEEPRL_TPU_TRACE_RUN"

PROFILE_DEFAULT_S = 3.0
PROFILE_MAX_S = 60.0


def trace_enabled() -> bool:
    return os.environ.get(TRACE_ENV, "1") != "0"


def new_run_id() -> str:
    return secrets.token_hex(4)


def ensure_run_id() -> str:
    """The run id every shard of one run shares. First caller (the
    learner's `Telemetry.from_args`) mints it and exports it through the
    environment; actor/serve subprocesses inherit the same value."""
    rid = os.environ.get(RUN_ENV)
    if not rid:
        rid = new_run_id()
        os.environ[RUN_ENV] = rid
    return rid


# per-emit span ids are hot-path (~3 per learner update); a private
# Random seeded from the OS is ~5x cheaper than secrets.token_hex and —
# unlike the global `random` state — immune to user code calling
# random.seed(k) in every process, which would collide ids across shards
_span_rng = random.Random(secrets.randbits(64))


def new_span_id() -> str:
    """Compact 8-hex-char span id — small enough to ride JSON frame meta
    on every PUSH without moving the payload-size needle."""
    return f"{_span_rng.getrandbits(32):08x}"


class Span:
    """One open span: `Tracer.begin` hands it out, `Tracer.end` emits it."""

    __slots__ = ("id", "name", "parent", "t0", "attrs")

    def __init__(self, sid: str, name: str, parent: str | None, t0: float):
        self.id = sid
        self.name = name
        self.parent = parent
        self.t0 = t0
        self.attrs: dict[str, Any] = {}

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self


class Tracer:
    """Span emitter bound to one Telemetry shard.

    Every method is a cheap no-op when tracing is off (kill switch) or
    the bound Telemetry is disabled, and every method tolerates a None
    span, so call sites never branch on enablement:

        span = tracer.begin("push", parent=collect_id)
        ...
        tracer.end(span, rows=rows)         # safe even if span is None
    """

    def __init__(self, telem: Any):
        self._telem = telem
        # the kill switch is an at-startup decision: read the environment
        # once here, not on every begin/end/point (an environ lookup per
        # span would be ~15% of the whole emit cost)
        self._env_on = trace_enabled()

    @property
    def enabled(self) -> bool:
        return self._env_on and bool(getattr(self._telem, "enabled", False))

    def begin(self, name: str, parent: str | None = None, **attrs: Any) -> Span | None:
        if not self.enabled:
            return None
        span = Span(new_span_id(), name, parent, time.time())
        span.attrs.update(attrs)
        return span

    def end(self, span: Span | None, **attrs: Any) -> str | None:
        if span is None or not self.enabled:
            return None
        span.attrs.update(attrs)
        t1 = time.time()
        self._telem.event(
            "span",
            name=span.name,
            span=span.id,
            parent=span.parent,
            t0=round(span.t0, 6),
            t1=round(t1, 6),
            dur_ms=round((t1 - span.t0) * 1000.0, 3),
            **span.attrs,
        )
        return span.id

    def point(
        self,
        name: str,
        parent: str | None = None,
        t0: float | None = None,
        **attrs: Any,
    ) -> str | None:
        """Emit a complete span in one call. With `t0` given the span
        covers [t0, now] (e.g. a wait measured by the caller); without,
        it is an instant."""
        if not self.enabled:
            return None
        t1 = time.time()
        sid = new_span_id()
        self._telem.event(
            "span",
            name=name,
            span=sid,
            parent=parent,
            t0=round(t1 if t0 is None else t0, 6),
            t1=round(t1, 6),
            dur_ms=round(0.0 if t0 is None else (t1 - t0) * 1000.0, 3),
            **attrs,
        )
        return sid


class ClockSync:
    """NTP-style clock-offset estimation over a request/reply exchange.

    The actor timestamps the request (`t0`) and the reply (`t1`) with its
    own wall clock; the peer stamps its reply with its wall clock
    (`server_wall`). Assuming symmetric latency,

        offset = server_wall - (t0 + t1) / 2       # peer = local + offset
        rtt    = t1 - t0

    and the minimum-RTT sample is the most trustworthy one (queuing only
    inflates RTT, never deflates it). Every improved sample is recorded
    as a ``trace.clock`` event so `sheeptrace` uses the best estimate a
    shard ever saw."""

    def __init__(self, telem: Any = None):
        self._telem = telem
        self._env_on = trace_enabled()
        self.offset_s: float | None = None
        self.rtt_s: float | None = None
        self.samples = 0

    def add(self, t0: float, server_wall: float, t1: float) -> bool:
        rtt = max(t1 - t0, 0.0)
        offset = server_wall - (t0 + t1) / 2.0
        self.samples += 1
        improved = self.rtt_s is None or rtt < self.rtt_s
        if improved:
            self.rtt_s = rtt
            self.offset_s = offset
            if self._telem is not None and self._env_on:
                self._telem.event(
                    "trace.clock",
                    offset_s=round(offset, 6),
                    rtt_s=round(rtt, 6),
                    samples=self.samples,
                )
        return improved


# ---------------------------------------------------------------------------
# on-demand profiling
# ---------------------------------------------------------------------------


class ProfileWindow:
    """A bounded `jax.profiler.trace` window that any live process can
    open on demand (PROFILE frame or SIGUSR2). One window at a time: an
    overlapping request is refused with the open window's path instead
    of corrupting the running trace. The stop side reuses the
    `StepProfiler` device barrier so async dispatch cannot cut the
    device timeline mid-step."""

    def __init__(self):
        self._lock = threading.Lock()
        self._dir: str | None = None
        self._timer: threading.Timer | None = None

    @property
    def active(self) -> bool:
        with self._lock:
            return self._dir is not None

    def request(self, out_dir: str, seconds: float = PROFILE_DEFAULT_S) -> dict:
        """Open a window into a fresh subdirectory of `out_dir`; a
        background timer closes it after `seconds`. Returns
        ``{ok, dir, seconds, pid}`` or ``{ok: False, error, ...}``."""
        seconds = min(max(float(seconds), 0.01), PROFILE_MAX_S)
        with self._lock:
            if self._dir is not None:
                return {
                    "ok": False,
                    "error": "profile window already open",
                    "dir": self._dir,
                    "pid": os.getpid(),
                }
            path = os.path.join(out_dir, f"window_{int(time.time() * 1000)}")
            try:
                os.makedirs(path, exist_ok=True)
                import jax

                jax.profiler.start_trace(path)
            except Exception as err:
                return {
                    "ok": False,
                    "error": f"{type(err).__name__}: {err}",
                    "pid": os.getpid(),
                }
            self._dir = path
            self._timer = threading.Timer(seconds, self.close)
            self._timer.daemon = True
            self._timer.start()
        from .core import emit

        emit(
            "profile.window.start",
            dir=path, seconds=seconds, pid=os.getpid(),
        )
        return {"ok": True, "dir": path, "seconds": seconds, "pid": os.getpid()}

    def close(self) -> None:
        """Stop the open window (timer path and explicit teardown share
        this; a second close on a closed window is a no-op)."""
        with self._lock:
            path, self._dir = self._dir, None
            timer, self._timer = self._timer, None
        if path is None:
            return
        if timer is not None:
            timer.cancel()
        try:
            import jax
            import jax.numpy as jnp

            # the StepProfiler barrier: per-device execution is FIFO, so
            # blocking on a fresh op drains everything dispatched before it
            for d in jax.local_devices():
                jax.block_until_ready(jnp.add(jax.device_put(0.0, d), 1.0))
        # sheeplint: disable=SL012 — a poisoned backend must not stop the
        # trace flush below
        except Exception:
            pass
        try:
            import jax

            jax.profiler.stop_trace()
        finally:
            from .core import emit

            emit("profile.window.stop", dir=path, pid=os.getpid())


_window = ProfileWindow()


def profile_window() -> ProfileWindow:
    """This process's shared on-demand window (frame + signal triggers
    must agree on the one-window-at-a-time rule)."""
    return _window


def handle_profile_frame(req: dict, default_dir: str | None = None) -> dict:
    """Serve one PROFILE frame request: ``{seconds?, dir?}`` -> the
    `ProfileWindow.request` reply. Shared by the flock service and the
    serve server so both answer identically."""
    import tempfile

    out_dir = req.get("dir") or os.path.join(
        default_dir or tempfile.mkdtemp(prefix="sheepscope-"),
        "profile_ondemand",
    )
    return _window.request(out_dir, req.get("seconds") or PROFILE_DEFAULT_S)


def install_profile_signal(
    log_dir: str, seconds: float = PROFILE_DEFAULT_S
) -> bool:
    """SIGUSR2 -> open a bounded profile window into
    `<log_dir>/profile_ondemand`. Main-thread only (CPython restricts
    signal.signal); returns False when it cannot install."""
    if threading.current_thread() is not threading.main_thread():
        return False

    def _on_sigusr2(_signum, _frame):
        reply = _window.request(os.path.join(log_dir, "profile_ondemand"), seconds)
        if not reply.get("ok"):
            # unlike the PROFILE frame, the signal has no channel to
            # return the refusal — surface it as a telemetry event
            from .core import emit

            emit("profile.window.error", trigger="sigusr2", **reply)

    try:
        signal.signal(signal.SIGUSR2, _on_sigusr2)
    except (ValueError, OSError, AttributeError):
        # non-main thread race or a platform without SIGUSR2
        return False
    return True
