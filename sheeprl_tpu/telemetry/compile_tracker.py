"""XLA recompilation tracking: jit retraces become a metric, not a mystery.

A mid-run recompile (a shape drifting, a weak_type flip, a python-scalar
static arg changing) silently costs seconds to minutes on TPU and the only
prior symptom was a dip in `Time/step_per_second`. `jax.monitoring` fires a
duration event per backend compile (`/jax/core/compile/
backend_compile_duration` on jax 0.4.x) plus tracing/lowering durations, so
counting those gives recompile count and total compile seconds with zero
instrumentation of the jitted functions themselves.

jax's listener registry is append-only (`clear_event_listeners` nukes
everyone's listeners, including jax's own internal ones), so ONE module-level
listener is installed lazily and forwards to the currently attached
`CompileTracker` instances — trackers attach/detach, the listener stays.

Fallback: on a jax without `jax.monitoring` (or with a renamed event key) the
tracker reports `supported=False` and zero counts rather than crashing; the
explicit shim alternative — wrapping `jit(...).lower().compile()` — only sees
AOT callers, so the monitoring path is primary and the absence is surfaced
honestly in the metrics (`XLA/recompiles` simply never appears).
"""

from __future__ import annotations

import threading

__all__ = ["CompileTracker", "monitoring_supported"]

# event-name fragments that mark one backend compile / its phases (jax 0.4.x
# emits /jax/core/compile/{jaxpr_trace,jaxpr_to_mlir_module,backend_compile}
# _duration; the backend_compile one fires exactly once per XLA compile)
_COMPILE_EVENT = "backend_compile_duration"
_COMPILE_PHASE_FRAGMENT = "/jax/core/compile/"

_lock = threading.Lock()
_trackers: set["CompileTracker"] = set()
_installed: bool | None = None  # None = not attempted, True/False = outcome


def monitoring_supported() -> bool:
    return _install_listener()


def _on_duration(name: str, secs: float, **kw) -> None:
    if _COMPILE_PHASE_FRAGMENT not in name:
        return
    is_compile = name.endswith(_COMPILE_EVENT)
    with _lock:
        for t in _trackers:
            t._record(secs, is_compile)


def _install_listener() -> bool:
    global _installed
    if _installed is not None:
        return _installed
    try:
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True
    except Exception:
        _installed = False
    return _installed


class CompileTracker:
    """Counts backend compiles and total compile-pipeline seconds (trace +
    lower + backend compile) seen while attached. `flush()` returns the
    interval delta plus running totals."""

    def __init__(self) -> None:
        self.supported = _install_listener()
        self._count = 0
        self._seconds = 0.0
        self._flushed_count = 0
        self._flushed_seconds = 0.0
        self._attached = False

    def attach(self) -> "CompileTracker":
        if self.supported and not self._attached:
            with _lock:
                _trackers.add(self)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            with _lock:
                _trackers.discard(self)
            self._attached = False

    # called from the module listener under _lock
    def _record(self, secs: float, is_compile: bool) -> None:
        if is_compile:
            self._count += 1
        self._seconds += secs

    def flush(self) -> dict[str, float]:
        """Interval delta + running totals since attach."""
        with _lock:
            count, seconds = self._count, self._seconds
        out = {
            "compiles": count - self._flushed_count,
            "compile_seconds": seconds - self._flushed_seconds,
            "total_compiles": count,
            "total_compile_seconds": seconds,
        }
        self._flushed_count, self._flushed_seconds = count, seconds
        return out
