"""Compile-latency subsystem (ISSUE 5): warm-start AOT compilation
overlapped with collection, one cache-arming path, and measured
partitioning of compile-pathological jits.

Import surface is jax-free at module load (the parent package arms the
persistent cache through here at import time, before jax config must be
touched); every jax import inside is lazy.
"""

from .cache import MIN_COMPILE_SECS, CacheStats, arm_compile_cache, default_cache_dir
from .decisions import (
    Decision,
    decide,
    decide_remat,
    decision_key,
    measured_probe,
    migrate_legacy_scan_unroll,
    remat_enabled,
    remat_mode,
)
from .partition import (
    PartitionDecision,
    chunk_for_budget,
    compiled_memory_stats,
    decide_batch_chunk,
    ledger_entry,
    lowered_op_counts,
    predicted_cpu_compile_seconds,
)
from .plan import CaptureComplete, CompilePlan, DataEdge, WarmJit, avals_of, sds
from .specs import dict_obs_spec, dreamer_sample_spec

__all__ = [
    "dict_obs_spec",
    "dreamer_sample_spec",
    "MIN_COMPILE_SECS",
    "CacheStats",
    "CaptureComplete",
    "CompilePlan",
    "DataEdge",
    "Decision",
    "PartitionDecision",
    "WarmJit",
    "arm_compile_cache",
    "avals_of",
    "chunk_for_budget",
    "compiled_memory_stats",
    "decide",
    "decide_batch_chunk",
    "decide_remat",
    "decision_key",
    "default_cache_dir",
    "ledger_entry",
    "lowered_op_counts",
    "measured_probe",
    "migrate_legacy_scan_unroll",
    "predicted_cpu_compile_seconds",
    "remat_enabled",
    "remat_mode",
    "sds",
]
