"""sheepopt decisions — ONE measured-decision framework for every tuning
knob (ISSUE 11 tentpole).

The repo grew its perf knobs one bespoke ladder at a time: the scan-unroll
autotuner (ISSUE 9) measured rungs and persisted winners in its own
`scan_unroll.json`; `decide_batch_chunk` (ISSUE 5/10) trial-compiled and
never persisted anything; the `--remat` flag stayed a human decision fed by
sheepmem's advisor. This module generalizes the PR-9 rung-ladder machinery
into the one shape they all share:

    a Decision = (knob family, candidate ladder, example avals)
        -> per-candidate trial `lower().compile()` (compile time measured
           apart from exec, the PR-5 AOT machinery),
        -> per-candidate exec timing at the run's EXACT shapes,
        -> per-candidate XLA `memory_analysis()` peak/temp bytes,
        -> per-candidate BIT-EXACTNESS receipt vs the baseline candidate
           (a non-bit-exact candidate is disqualified, never silently kept),
        -> a winner under an explicit objective: `seconds` (fastest),
           `bytes` (smallest peak), or bytes-at-<=X%-time-cost (smallest
           peak among candidates within the time budget),
        -> persisted in ONE decision cache next to the compile cache
           (`decisions.json`, keyed family|name|avals|jax version|backend),
           so a re-run with the same key skips every trial compile exactly
           like a warm compile cache skips the compile.

Actuators built on top: `decide_remat` (the auto-remat acceptance gate:
peak-bytes reduction at <=5% exec-time cost), the migrated scan-unroll
ladder (`ops/scan.py:autotune_unroll`), and `decide_batch_chunk`'s
measured path (`measured_probe` memoizes its trial compile). Every future
knob (precision islands, chunk ladders, prefetch depths) gets trial
compiles + receipts + caching for free by naming a family and a ladder.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from typing import Any, Callable, Iterator, Sequence

__all__ = [
    "CandidateReport",
    "Decision",
    "REMAT_LADDER",
    "cache_path",
    "decide",
    "decide_remat",
    "decision_key",
    "load_cache",
    "measured_probe",
    "migrate_legacy_scan_unroll",
    "remat_enabled",
    "remat_mode",
    "remat_time_cost_frac",
]

CACHE_BASENAME = "decisions.json"
LEGACY_SCAN_UNROLL_BASENAME = "scan_unroll.json"

# The auto-remat acceptance gate: remat wins only when it reduces peak
# bytes AND costs at most this fraction of the baseline's exec time.
DEFAULT_REMAT_TIME_COST_FRAC = 0.05


def remat_time_cost_frac() -> float:
    try:
        return float(
            os.environ.get(
                "SHEEPRL_TPU_REMAT_TIME_COST_FRAC", DEFAULT_REMAT_TIME_COST_FRAC
            )
        )
    except ValueError:
        return DEFAULT_REMAT_TIME_COST_FRAC


def remat_mode(value: Any) -> str:
    """The `--remat {off,on,policy,auto}` knob as the settled mode the
    trace sites consume: `on` = full `jax.checkpoint` of the scan body,
    `policy` = checkpoint with `dots_with_no_batch_dims_saveable` (matmul
    outputs stay saved, only cheap elementwise ops recompute — the
    bytes-at-near-zero-time-cost rung), `off` = store everything. `auto`
    reads "off" here: the mains resolve it via `decide_remat` BEFORE
    tracing, so an unresolved `auto` (e.g. a capture run that never
    reaches the decision) means baseline. Bools pass through for
    pre-ISSUE-11 checkpoints that stored one."""
    if isinstance(value, bool):
        return "on" if value else "off"
    v = str(value).strip().lower()
    if v in ("on", "true", "1", "yes"):
        return "on"
    if v == "policy":
        return "policy"
    return "off"


def remat_enabled(value: Any) -> bool:
    """True when the settled remat mode checkpoints anything at all."""
    return remat_mode(value) != "off"


# ---------------------------------------------------------------------------
# the decision cache: one store next to the compile cache
# ---------------------------------------------------------------------------


def cache_path(explicit: str | None = None) -> str:
    """The unified decision store lives next to the persistent compile
    cache — same resolution order as compile/cache.py, without arming
    anything."""
    if explicit:
        return explicit
    base = (
        os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    )
    if not base:
        from .cache import default_cache_dir

        base = default_cache_dir()
    return os.path.join(base, CACHE_BASENAME)


def load_cache(path: str) -> dict:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except Exception:
        return {}


def _save_cache(path: str, store: dict) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(store, fh, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass  # the store is an optimization; never fail the run on it


def _avals_tag(example: Sequence[Any]) -> str:
    import jax

    return ",".join(
        f"{getattr(getattr(a, 'dtype', None), 'name', type(a).__name__)}"
        f"{list(getattr(a, 'shape', []))}"
        for a in jax.tree_util.tree_leaves(example)
    )


def decision_key(family: str, name: str, example: Sequence[Any]) -> str:
    """The cache key: knob family + probe name + exact avals + jax version
    + backend. Any drift in any component is a miss — a decision measured
    on other shapes, another toolchain, or another chip never leaks."""
    import jax

    return (
        f"{family}|{name}|{_avals_tag(example)}"
        f"|jax{jax.__version__}|{jax.default_backend()}"
    )


def migrate_legacy_scan_unroll(
    store_path: str, legacy_path: str | None = None
) -> int:
    """One-shot migration of a pre-ISSUE-11 `scan_unroll.json` winner store
    into the unified decision cache: every legacy entry (key schema
    `name|avals|jaxX|backend`) is rewritten under the new schema
    (`scan_unroll|` prefix) as a full Decision record, the legacy file is
    removed, and the count of migrated entries returned. Entries already
    present in the unified cache win (they may be fresher). No-op (0) when
    no legacy file exists or the store path IS the legacy name."""
    if os.path.basename(store_path) == LEGACY_SCAN_UNROLL_BASENAME:
        return 0  # an explicit store at the legacy name is not a legacy store
    if legacy_path is None:
        legacy_path = os.path.join(
            os.path.dirname(store_path) or ".", LEGACY_SCAN_UNROLL_BASENAME
        )
    legacy = load_cache(legacy_path)
    if not legacy:
        return 0
    store = load_cache(store_path)
    migrated = 0
    for old_key, rec in legacy.items():
        new_key = f"scan_unroll|{old_key}"
        if new_key in store or not isinstance(rec, dict) or "winner" not in rec:
            continue
        candidates = {}
        for rung, secs in rec.get("timings_s", {}).items():
            candidates[str(rung)] = {
                "exec_seconds": float(secs),
                "compile_seconds": float(rec.get("compile_s", {}).get(rung, 0.0)),
                "bit_exact": bool(rec.get("bit_exact", {}).get(rung, True)),
                "peak_bytes": None,
                "temp_bytes": None,
            }
        store[new_key] = Decision(
            family="scan_unroll",
            name=str(rec.get("probe") or rec.get("name") or ""),
            winner=str(rec["winner"]),
            baseline="1",
            objective="seconds",
            candidates=candidates,
            accepted=str(rec["winner"]) != "1",
            source="cache",
            key=new_key,
        ).as_dict()
        migrated += 1
    if migrated:
        _save_cache(store_path, store)
    try:
        os.remove(legacy_path)
    except OSError:
        pass
    return migrated


# ---------------------------------------------------------------------------
# the Decision record
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CandidateReport:
    """One rung of one ladder: what it cost to build, what it costs to run,
    what it holds live, and whether its numerics survived the receipt."""

    label: str
    exec_seconds: float | None = None
    compile_seconds: float | None = None
    bit_exact: bool | None = None
    peak_bytes: int | None = None
    temp_bytes: int | None = None
    error: str | None = None
    # bounded-divergence acceptance (ISSUE 20): when the ladder runs with a
    # quality_metric, every candidate carries its measured divergence vs the
    # baseline and whether it stayed within quality_bound. Bit-exact ladders
    # leave both None — the receipt is bit-exactness, as before.
    divergence: float | None = None
    within_bound: bool | None = None

    def as_dict(self) -> dict[str, Any]:
        return {k: v for k, v in dataclasses.asdict(self).items() if k != "label"}


@dataclasses.dataclass
class Decision:
    """One measured ladder and its accepted winner. `accepted` means the
    winner differs from the baseline — the knob actually moved."""

    family: str
    name: str
    winner: str  # label of the winning candidate
    baseline: str  # label of the reference candidate (receipts compare to it)
    objective: str  # "seconds" | "bytes"
    candidates: dict[str, dict]  # label -> CandidateReport.as_dict()
    accepted: bool
    source: str  # "measured" | "cache"
    key: str
    max_time_cost_frac: float | None = None
    # the quality-receipt bound the ladder was accepted under (None for
    # bit-exact ladders) — committed next to the winner so the cache entry
    # IS the receipt
    quality_bound: float | None = None

    def candidate(self, label: str) -> dict:
        return self.candidates.get(str(label), {})

    def seconds_delta(self) -> float | None:
        """Winner exec seconds minus baseline (negative = faster)."""
        w = self.candidate(self.winner).get("exec_seconds")
        b = self.candidate(self.baseline).get("exec_seconds")
        if w is None or b is None:
            return None
        return float(w) - float(b)

    def bytes_delta(self) -> int | None:
        """Winner peak bytes minus baseline (negative = smaller)."""
        w = self.candidate(self.winner).get("peak_bytes")
        b = self.candidate(self.baseline).get("peak_bytes")
        if w is None or b is None:
            return None
        return int(w) - int(b)

    def as_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def as_event(self) -> dict[str, Any]:
        """The telemetry payload: compact — the full per-candidate ladder
        stays in the cache, the event carries the decision."""
        out = {
            "family": self.family,
            "probe": self.name,
            "winner": self.winner,
            "baseline": self.baseline,
            "objective": self.objective,
            "accepted": bool(self.accepted),
            "source": self.source,
            "candidates_tried": len(self.candidates),
        }
        sd, bd = self.seconds_delta(), self.bytes_delta()
        if sd is not None:
            out["seconds_delta"] = sd
        if bd is not None:
            out["bytes_delta"] = bd
        if self.quality_bound is not None:
            out["quality_bound"] = self.quality_bound
            div = self.candidate(self.winner).get("divergence")
            if div is not None:
                out["divergence"] = div
        return out

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Decision":
        return cls(
            family=str(d.get("family", "")),
            name=str(d.get("name", "")),
            winner=str(d.get("winner", "")),
            baseline=str(d.get("baseline", "")),
            objective=str(d.get("objective", "seconds")),
            candidates={str(k): dict(v) for k, v in d.get("candidates", {}).items()},
            accepted=bool(d.get("accepted", False)),
            source="cache",
            key=str(d.get("key", "")),
            max_time_cost_frac=d.get("max_time_cost_frac"),
            quality_bound=d.get("quality_bound"),
        )


def cached_decision(path: str, key: str) -> Decision | None:
    rec = load_cache(path).get(key)
    if not isinstance(rec, dict) or "candidates" not in rec:
        return None
    return Decision.from_dict({**rec, "key": key})


def _store(path: str, key: str, record: dict) -> None:
    store = load_cache(path)
    store[key] = record
    _save_cache(path, store)


# ---------------------------------------------------------------------------
# the measurement loop
# ---------------------------------------------------------------------------


def _bit_exact(a: Any, b: Any) -> bool:
    import jax
    import numpy as np

    la = [np.asarray(x) for x in jax.tree_util.tree_leaves(a)]
    lb = [np.asarray(x) for x in jax.tree_util.tree_leaves(b)]
    if len(la) != len(lb):
        return False
    return all(np.array_equal(x, y, equal_nan=True) for x, y in zip(la, lb))


@contextlib.contextmanager
def _null_context(_value: Any) -> Iterator[None]:
    yield


def _absorb_process_warmup(fn: Callable, example: Sequence[Any]) -> None:
    """A throwaway lower + trivial compile absorb the process's one-time
    tracing/MLIR/LLVM-backend warmup so it doesn't bias the first
    candidate's compile_seconds (the same first-call attribution trap as
    the r4/r5 compile-vs-exec mixup)."""
    import jax
    import jax.numpy as jnp

    jax.jit(lambda *a: fn(*a)).lower(*example)
    jax.block_until_ready(jax.jit(lambda v: v + 1.0)(jnp.float32(0.0)))


def decide(
    family: str,
    name: str,
    candidates: Sequence[Any],
    build: Callable[[Any], Callable],
    example: Sequence[Any],
    *,
    objective: str = "seconds",
    max_time_cost_frac: float | None = None,
    repeats: int = 3,
    store_path: str | None = None,
    force: bool = False,
    candidate_context: Callable[[Any], Any] | None = None,
    quality_metric: Callable[[Any, Any], float] | None = None,
    quality_bound: float | None = None,
) -> Decision:
    """Measure one candidate ladder and return (and persist) the decision.

    `build(candidate)` must return a JITtable callable for that candidate —
    a FRESH callable per call (jax's trace cache keys on function identity,
    so reusing one callable across candidates would silently measure the
    first candidate N times; `decide` wraps defensively anyway).
    `candidate_context(candidate)` (optional) is entered around the
    candidate's trace/compile/exec so trace-time knobs (the unroll
    override) see the candidate value.

    Per candidate: AOT `lower().compile()` (compile time measured apart
    from exec), `memory_analysis()` peak/temp bytes, one untimed warm-up
    call, then `repeats` timed calls (median). The FIRST candidate is the
    baseline: any candidate whose outputs are not bit-identical to it is
    disqualified. Winner selection by `objective`:

      - "seconds": fastest surviving candidate; ties break toward ladder
        order (callers list cheaper/simpler candidates first);
      - "bytes": smallest peak-bytes among surviving candidates whose exec
        time is within `max_time_cost_frac` of the baseline's (when set);
        a candidate must STRICTLY undercut the baseline's bytes to win.

    Bounded-divergence acceptance (the quantization path): passing
    `quality_metric` (a `(baseline_out, candidate_out) -> float` distance,
    e.g. max action divergence over a held-out calibration set) together
    with `quality_bound` relaxes the receipt — a non-bit-exact candidate
    survives when its measured divergence stays <= `quality_bound`, and is
    DISQUALIFIED past it exactly like a non-bit-exact remat rung. The
    divergence and the bound persist in the cache record: the decision
    entry IS the quality receipt.
    """
    import jax

    from .partition import compiled_memory_stats

    if objective not in ("seconds", "bytes"):
        raise ValueError(f"unknown objective {objective!r}")
    if (quality_metric is None) != (quality_bound is None):
        raise ValueError("quality_metric and quality_bound come together")
    labels = [str(c) for c in candidates]
    if len(set(labels)) != len(labels):
        raise ValueError(f"duplicate candidate labels in {labels}")
    path = cache_path(store_path)
    key = decision_key(family, name, example)
    if not force:
        hit = cached_decision(path, key)
        if hit is not None:
            return hit

    ctx = candidate_context or _null_context
    reports: dict[str, CandidateReport] = {}
    outputs: dict[str, Any] = {}

    with ctx(candidates[0]):
        _absorb_process_warmup(build(candidates[0]), example)
    for value, label in zip(candidates, labels):
        report = CandidateReport(label=label)
        reports[label] = report
        try:
            fn = build(value)
            fresh = lambda *a: fn(*a)  # noqa: E731 — fresh trace identity
            with ctx(value):
                t0 = time.perf_counter()
                # sheeplint: disable=SL004 — a fresh jit per candidate is
                # the POINT: each candidate must trace its own program, and
                # the ladder runs once per (family, shapes, backend) key
                compiled = jax.jit(fresh).lower(*example).compile()
                report.compile_seconds = time.perf_counter() - t0
                mem = compiled_memory_stats(compiled)
                if mem is not None:
                    report.peak_bytes = mem["peak_bytes"]
                    report.temp_bytes = mem["temp_bytes"]
                out = jax.block_until_ready(compiled(*example))  # warm-up
                samples = []
                for _ in range(max(1, repeats)):
                    t0 = time.perf_counter()
                    out = jax.block_until_ready(compiled(*example))
                    samples.append(time.perf_counter() - t0)
        except Exception as err:  # a broken candidate loses, never aborts
            report.error = f"{type(err).__name__}: {err}"[:200]
            continue
        samples.sort()
        report.exec_seconds = samples[len(samples) // 2]
        outputs[label] = out

    baseline = labels[0]
    if baseline not in outputs:
        raise RuntimeError(
            f"{family}/{name}: baseline candidate {baseline!r} failed to "
            f"compile or run: {reports[baseline].error}"
        )
    for label in labels:
        if label not in outputs:
            reports[label].bit_exact = False
            if quality_metric is not None:
                reports[label].within_bound = False
            continue
        reports[label].bit_exact = (
            True if label == baseline else _bit_exact(outputs[baseline], outputs[label])
        )
        if quality_metric is not None:
            if label == baseline:
                reports[label].divergence = 0.0
                reports[label].within_bound = True
            else:
                try:
                    div = float(quality_metric(outputs[baseline], outputs[label]))
                except Exception as err:  # an unmeasurable receipt disqualifies
                    reports[label].error = f"{type(err).__name__}: {err}"[:200]
                    reports[label].within_bound = False
                    continue
                reports[label].divergence = div
                reports[label].within_bound = div <= quality_bound

    winner = _pick_winner(
        labels, reports, objective, baseline, max_time_cost_frac
    )
    decision = Decision(
        family=family,
        name=name,
        winner=winner,
        baseline=baseline,
        objective=objective,
        candidates={lbl: rep.as_dict() for lbl, rep in reports.items()},
        accepted=winner != baseline,
        source="measured",
        key=key,
        max_time_cost_frac=max_time_cost_frac,
        quality_bound=quality_bound,
    )
    _store(path, key, decision.as_dict())
    return decision


def _pick_winner(
    labels: list[str],
    reports: dict[str, CandidateReport],
    objective: str,
    baseline: str,
    max_time_cost_frac: float | None,
) -> str:
    # a candidate survives on either receipt: bit-exactness (the default)
    # or a measured divergence within the quality bound (bounded
    # acceptance); everything else is disqualified
    eligible = [
        lbl
        for lbl in labels
        if (reports[lbl].bit_exact or reports[lbl].within_bound)
        and reports[lbl].exec_seconds is not None
    ]
    if objective == "seconds":
        return min(
            eligible, key=lambda lbl: (reports[lbl].exec_seconds, labels.index(lbl))
        )
    # objective == "bytes": strictly fewer peak bytes than baseline, within
    # the exec-time budget when one is set
    base = reports[baseline]
    best = baseline
    if base.peak_bytes is None:
        return baseline  # no memory analysis on this backend: keep baseline
    budget_s = (
        None
        if max_time_cost_frac is None or base.exec_seconds is None
        else base.exec_seconds * (1.0 + max_time_cost_frac)
    )
    for lbl in eligible:
        rep = reports[lbl]
        if lbl == baseline or rep.peak_bytes is None:
            continue
        if budget_s is not None and rep.exec_seconds > budget_s:
            continue
        if rep.peak_bytes < reports[best].peak_bytes:
            best = lbl
    return best


# ---------------------------------------------------------------------------
# actuator: auto-remat (ISSUE 11 tentpole a)
# ---------------------------------------------------------------------------


REMAT_LADDER = ("off", "policy", "on")


def decide_remat(
    name: str,
    build: Callable[[str], Callable],
    example: Sequence[Any],
    *,
    candidates: Sequence[str] = REMAT_LADDER,
    repeats: int = 3,
    store_path: str | None = None,
    force: bool = False,
    max_time_cost_frac: float | None = None,
) -> Decision:
    """The auto-remat acceptance gate: `build(mode)` returns the
    scan-bearing probe (typically a grad of the train step's dominant
    scan) with the scan body checkpointed per `mode` ("off" / "policy" =
    dots-saveable policy / "on" = full checkpoint; `remat_mode` +
    `ops.scan.checkpoint_body` are the shared plumbing). A remat rung is
    accepted only when it STRICTLY reduces `memory_analysis()` peak
    bytes, costs at most `max_time_cost_frac` (default 5%,
    SHEEPRL_TPU_REMAT_TIME_COST_FRAC) of the baseline's exec time, and is
    bit-exact vs the non-remat baseline — full remat typically buys the
    most bytes but pays a whole recomputed forward, so on exec-bound
    hosts the policy rung is the expected winner. The winner persists in
    the unified decision cache."""
    frac = remat_time_cost_frac() if max_time_cost_frac is None else max_time_cost_frac
    return decide(
        "remat",
        name,
        list(candidates),
        build,
        example,
        objective="bytes",
        max_time_cost_frac=frac,
        repeats=repeats,
        store_path=store_path,
        force=force,
    )


# ---------------------------------------------------------------------------
# measured probes: memoized one-off measurements (batch-chunk's trial)
# ---------------------------------------------------------------------------


def measured_probe(
    family: str,
    name: str,
    example: Sequence[Any],
    measure: Callable[[], dict],
    *,
    store_path: str | None = None,
    force: bool = False,
) -> tuple[dict, str]:
    """Memoize one expensive measurement (a trial compile, a lowering
    sweep) in the unified decision cache, keyed exactly like a ladder
    decision. Returns `(record, source)` with source `"measured"` or
    `"cache"`. The record must be JSON-serializable; the DECISION derived
    from it (e.g. the batch chunk) is recomputed by the caller from
    current budgets, so a budget change never serves a stale decision —
    only the measurement is cached."""
    path = cache_path(store_path)
    key = decision_key(family, name, example)
    if not force:
        rec = load_cache(path).get(key)
        if isinstance(rec, dict) and "probe" in rec:
            return dict(rec["probe"]), "cache"
    record = measure()
    if not record.get("error"):  # failed measurements re-probe next call
        _store(
            path, key, {"family": family, "name": name, "key": key, "probe": record}
        )
    return record, "measured"
