"""The ONE persistent-compilation-cache arming path (ISSUE 5 satellite).

Before this module there were two competing cache-arming sites with two
different thresholds: `sheeprl_tpu/__init__._enable_compilation_cache`
(min_compile_time 0.5 s, armed at import) and
`parallel/mesh.distributed_setup` (re-armed with 10.0 s when
SHEEPRL_TPU_COMPILE_CACHE was set — so after distributed setup every
executable compiling in 0.5-10 s silently stopped being cached, exactly the
mid-cost policy/eval jits the warm-start subsystem wants to find on disk).
`bench.py` carried a third copy of the 10 s arm. All three now call
:func:`arm_compile_cache`; the single threshold lives in
:data:`MIN_COMPILE_SECS`.

Directory resolution order (first hit wins):

  1. the explicit ``path`` argument;
  2. ``SHEEPRL_TPU_COMPILE_CACHE`` (the runner/bench shared location);
  3. ``JAX_COMPILATION_CACHE_DIR`` (jax's own env var);
  4. a per-user tmpdir default (``<tmpdir>/sheeprl_tpu_xla_cache_<uid>`` —
     a fixed name in world-writable /tmp invites permission collisions and
     cache poisoning, since entries are deserialized executables).

``SHEEPRL_TPU_XLA_CACHE=0`` disables the cache entirely (arm_compile_cache
returns None and touches nothing).

Cache hit/miss observability rides jax.monitoring: jax 0.4.x records
``/jax/compilation_cache/cache_hits`` / ``cache_misses`` events per backend
compile, and :class:`CacheStats` counts them with the same
attach/detach-listener pattern as telemetry's CompileTracker (jax's listener
registry is append-only, so ONE module-level listener forwards to attached
instances).
"""

from __future__ import annotations

import os
import threading

__all__ = ["MIN_COMPILE_SECS", "arm_compile_cache", "default_cache_dir", "CacheStats"]

# The single compile-time floor below which executables are not persisted:
# sub-half-second compiles recompile faster than a cache round-trip and would
# bloat the cache. Everything at or above it — including the 0.5-10 s
# mid-cost executables the old distributed_setup arm silently dropped — is
# cached.
MIN_COMPILE_SECS = 0.5


def default_cache_dir() -> str:
    import tempfile

    uid = getattr(os, "getuid", lambda: "u")()
    return os.path.join(tempfile.gettempdir(), f"sheeprl_tpu_xla_cache_{uid}")


def arm_compile_cache(
    path: str | None = None,
    *,
    min_compile_secs: float | None = None,
    export_env: bool = True,
) -> str | None:
    """Point jax's persistent compilation cache at one directory with one
    threshold. Returns the armed path, or None when the cache is disabled
    (``SHEEPRL_TPU_XLA_CACHE=0``) or jax is unavailable. Safe to call
    repeatedly (idempotent re-arm with identical config).

    ``export_env=True`` (default) also exports ``JAX_COMPILATION_CACHE_DIR``
    so subprocesses (benches, spawned env workers, CLI runs under test)
    share the same cache instead of creating their own.

    ``min_compile_secs`` overrides :data:`MIN_COMPILE_SECS` — tests use 0.0
    to cache tiny graphs; production callers should not pass it.
    """
    if os.environ.get("SHEEPRL_TPU_XLA_CACHE", "1") == "0":
        return None
    path = (
        path
        or os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or default_cache_dir()
    )
    floor = MIN_COMPILE_SECS if min_compile_secs is None else min_compile_secs
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        # no size floor; the compile-time floor is the only gate
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", floor)
        if export_env:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    except Exception:
        return None  # never block import/setup on cache wiring
    return path


# ---------------------------------------------------------------------------
# Hit/miss counting (module-level listener, instances attach/detach)
# ---------------------------------------------------------------------------

_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_stats: set["CacheStats"] = set()
_installed: bool | None = None


def _on_event(name: str, **kw) -> None:
    if name == _HIT_EVENT:
        with _lock:
            for s in _stats:
                s._hits += 1
    elif name == _MISS_EVENT:
        with _lock:
            for s in _stats:
                s._misses += 1


def _install_listener() -> bool:
    global _installed
    if _installed is not None:
        return _installed
    try:
        import jax.monitoring

        jax.monitoring.register_event_listener(_on_event)
        _installed = True
    except Exception:
        _installed = False
    return _installed


class CacheStats:
    """Counts persistent-cache hits and misses seen while attached."""

    def __init__(self) -> None:
        self.supported = _install_listener()
        self._hits = 0
        self._misses = 0
        self._attached = False

    def attach(self) -> "CacheStats":
        if self.supported and not self._attached:
            with _lock:
                _stats.add(self)
            self._attached = True
        return self

    def detach(self) -> None:
        if self._attached:
            with _lock:
                _stats.discard(self)
            self._attached = False

    def snapshot(self) -> dict[str, int]:
        with _lock:
            return {"hits": self._hits, "misses": self._misses}
