"""Measured partitioning of compile-pathological jits (XLA:CPU conv grads).

The SAC-AE reconstruction update is the canonical pathology: one jit holding
a conv encoder/decoder forward+backward plus five optimizers compiles in
seconds on TPU but stalls XLA:CPU for minutes-to-hours at pixel sizes
(VERDICT r5: 951 s of a 1,037 s startup attributed to the recon jit at
batch 32 / 128 units; an unexplained >2.5 h outlier at the same nominal
scale). `--split_update` (per-model jits) removes the cross-model fusion
blowup but the recon jit alone still scales with BATCH: measured on the
round-6 dev host, first-call time of the isolated recon jit is 81 s at
batch 2 and 176 s at batch 4 at constant op count (23 stablehlo
convolutions, 1756 ops — the lowering is batch-invariant; the cost is in
XLA:CPU's conv-grad compilation, roughly linear in batch elements per
convolution).

That measurement is the heuristic: lower the candidate jit (sub-second),
count its convolutions, and predict

    compile_seconds ~= CPU_SECONDS_PER_CONV_ELEMENT * convolutions * batch

If the prediction exceeds the compile budget, partition the batch axis with
a PYTHON-level chunk loop over ONE chunk-sized executable (gradient
accumulation across chunks — see sac_ae's `chunked_recon`). In-jit loop
constructs do NOT work: `lax.map` with a batch-1 body still compiled in
173 s vs 176 s unchunked (measured), i.e. XLA:CPU pays the pathological
cost on the traced-through batch regardless of loop structure. A separate
chunk-sized executable really does compile at chunk cost (81 s at batch 2
on the same program). The chunk size is the largest batch divisor whose
predicted compile fits the budget. Nothing here is algorithm-specific: any
main can ask :func:`decide_batch_chunk` about any jit.

Attribution (round-6 isolation sweep, all at batch 4 / 64x64x9 pixels):
first call of the full recon-loss gradient 182 s; DECODER-only gradient
212 s; encoder-only gradient 3.1 s; forward-only 1.4 s; full grad at
cnn_channels_multiplier 4 instead of 16: 6.2 s. Separating the phases with
the AOT path (`lower().compile()` vs a timed call of the Compiled) then
showed that on THIS toolchain (jaxlib 0.4.36 XLA:CPU) the conv-grad
*compile* is flat in batch (1.5-2.7 s at batch 2 through 32) and the
scaling cost is EXECUTION of the transposed-conv gradient kernels
(~40 s/image at multiplier 16, superlinear in channels ~(C1/C0)^2.4) —
which resolves the VERDICT r5 951 s-vs->2.5 h "compile" discrepancy: the
number was execution (batch x per-image cost x host speed, and swappable
under memory pressure), conflated with compile by first-call timing. The
partition therefore decides on MEASURED quantities that still matter:

  - peak temp memory of the compiled executable (XLA's own
    `memory_analysis()`, read off a cheap trial AOT compile): batch-32
    conv-grad activations at pixel scale run to GiB — the memory-pressure
    path behind the 2.5 h outlier — and chunking divides them by
    batch/chunk;
  - trial compile seconds, for toolchains where conv-grad compile IS
    superlinear (the conv-count x batch predictor guards the trial so a
    pathological toolchain is not probed at full batch).

Budgets: SHEEPRL_TPU_COMPILE_BUDGET_S (default 120 s) and
SHEEPRL_TPU_PARTITION_MEM_MB (default 512 MiB).

Since ISSUE 10 the committed sheepmem ledger (`analysis/budget/`, section
`memory`) is the PREFERRED decision input: when the caller names its jit's
ledger key, the measured `memory_analysis()` temp bytes — scaled from the
capture avals to the live config by argument-byte ratio — decide the chunk
directly, with the conv-count predictor cross-validating from the
committed primitive histogram. The lower/trial-compile ladder below
remains the fallback for jits without a ledger entry.
"""

from __future__ import annotations

import os
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable

from .plan import avals_of

__all__ = [
    "CPU_SECONDS_PER_CONV_ELEMENT",
    "DEFAULT_COMPILE_BUDGET_S",
    "PartitionDecision",
    "chunk_for_budget",
    "compiled_memory_stats",
    "decide_batch_chunk",
    "ledger_entry",
    "lowered_op_counts",
    "partition_mem_budget_bytes",
    "predicted_cpu_compile_seconds",
]

# The compile-time predictor that GUARDS the trial compile. On the measured
# toolchain (jaxlib 0.4.36) conv-grad compile is flat in batch (~0.1 s per
# convolution, 2.3 s for the 23-conv recon at any batch), so this linear
# model is a deliberate over-estimate: it only blocks the trial compile on
# a toolchain whose conv-grad compile really is superlinear (the r4 dev-host
# report this subsystem was originally sized for).
CPU_SECONDS_PER_CONV_ELEMENT = 0.05

# Default per-jit compile budget the chunk chooser targets on XLA:CPU. The
# bounded receipt runners use ~900 s whole-run budgets, so a single jit
# predicted over 2 min is already pathological.
DEFAULT_COMPILE_BUDGET_S = 120.0


def compile_budget_s() -> float:
    try:
        return float(
            os.environ.get("SHEEPRL_TPU_COMPILE_BUDGET_S", DEFAULT_COMPILE_BUDGET_S)
        )
    except ValueError:
        return DEFAULT_COMPILE_BUDGET_S


def lowered_op_counts(fn: Callable, *example: Any) -> dict[str, int]:
    """Lower `fn` (jitted) at the example's avals — sub-second, no backend
    compile — and count the ops that drive XLA:CPU compile cost."""
    lowered = fn.lower(*avals_of(example))
    text = lowered.as_text()
    return {
        "convolutions": text.count("stablehlo.convolution"),
        "dots": text.count("stablehlo.dot"),
        "ops": text.count(" = "),
    }


def predicted_cpu_compile_seconds(convolutions: int, batch: int) -> float:
    return CPU_SECONDS_PER_CONV_ELEMENT * convolutions * max(batch, 1)


def chunk_for_budget(batch: int, convolutions: int, budget_s: float) -> int:
    """Largest divisor of `batch` whose predicted compile fits the budget
    (0 = no chunking needed). Divisors only: a ragged tail chunk would be a
    SECOND compiled body, paying the pathology twice."""
    if batch <= 1 or predicted_cpu_compile_seconds(convolutions, batch) <= budget_s:
        return 0
    best = 1
    for c in range(batch - 1, 0, -1):
        if batch % c == 0 and predicted_cpu_compile_seconds(convolutions, c) <= budget_s:
            best = c
            break
    return best if best < batch else 0


@dataclass
class PartitionDecision:
    """What the measured heuristic decided for one jit, and why — surfaced
    in telemetry (`compile.partition` event) so a receipt run records the
    decision inputs, not just the outcome."""

    chunk: int  # 0 = leave unpartitioned
    backend: str
    batch: int
    predicted_seconds: float
    budget_s: float
    counts: dict[str, int] = field(default_factory=dict)
    reason: str = ""

    def as_event(self) -> dict[str, Any]:
        return {
            "chunk": self.chunk,
            "backend": self.backend,
            "batch": self.batch,
            "predicted_seconds": round(self.predicted_seconds, 1),
            "budget_s": self.budget_s,
            **{f"count_{k}": v for k, v in self.counts.items()},
            "reason": self.reason,
        }


def partition_mem_budget_bytes() -> int:
    try:
        mb = float(os.environ.get("SHEEPRL_TPU_PARTITION_MEM_MB", "512"))
    except ValueError:
        mb = 512.0
    return int(mb * 2**20)


def compiled_memory_stats(compiled: Any) -> dict[str, int] | None:
    """XLA's `memory_analysis()` of a Compiled, as plain ints (None when
    the backend does not expose it). `peak_bytes` is the bytes one dispatch
    must have provisioned: arguments + outputs + temps + generated code.
    `alias_size_in_bytes` is deliberately not netted out — XLA reports it
    only on fresh compiles (persistent-cache deserializations return 0),
    so subtracting it makes the number drift with cache state."""
    try:
        ma = compiled.memory_analysis()
        arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
        out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
        temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
        gen = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    except Exception:
        return None
    return {
        "peak_bytes": arg + out + temp + gen,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "generated_code_bytes": gen,
    }


# ---------------------------------------------------------------------------
# the committed memory ledger as a decision input (ISSUE 10)
# ---------------------------------------------------------------------------


def _budget_dir() -> str:
    default = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        "analysis",
        "budget",
    )
    return os.environ.get("SHEEPRL_TPU_BUDGET_DIR", default)


def ledger_entry(key: str, section: str = "memory") -> dict | None:
    """The committed `analysis/budget/` entry for `key` ('spec/jit'), from
    the given section — stdlib JSON only, None on any miss. This is how
    the partition heuristic reads sheepmem's measured bytes without
    importing the analysis package (which imports this module)."""
    import json

    spec = key.split("/", 1)[0]
    path = os.path.join(_budget_dir(), f"{spec}.json")
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh).get(section, {}).get(key)
    except (OSError, ValueError):
        return None


def _example_arg_bytes(example: tuple) -> int:
    """Total argument bytes of an example's avals — cheap (no lowering),
    used to scale the ledger's measured temp bytes from the tiny capture
    avals to the live config."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(avals_of(example)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * int(getattr(dtype, "itemsize", 4))
    return total


def _chunk_for_ratio(batch: int, ratio: float) -> int:
    """Largest divisor of `batch` at or below `batch * ratio` (>=1)."""
    target = max(int(batch * min(ratio, 1.0)), 1)
    for c in range(target, 0, -1):
        if batch % c == 0:
            return c
    return 1


def decide_batch_chunk(
    fn: Callable,
    example: tuple,
    batch: int,
    budget_s: float | None = None,
    backend: str | None = None,
    mem_budget_bytes: int | None = None,
    ledger_key: str | None = None,
    store_path: str | None = None,
) -> PartitionDecision:
    """Measure `fn` and decide whether (and how finely) to partition its
    batch axis on this backend. Non-CPU backends never partition — TPU
    compiles and runs the fused program fine and prefers the fusion.

    The decision ladder on CPU:
      0. `ledger_key` ('spec/jit') names a committed sheepmem fingerprint:
         its MEASURED temp bytes, scaled from the capture avals to the
         live config by argument-byte ratio, decide the chunk directly —
         byte-driven, zero lowering, zero trial compile. The conv-count x
         batch predictor still cross-validates from the committed
         primitive histogram (a superlinear-compile toolchain chunks by
         whichever constraint is tighter);
      1. no ledger entry: lower (sub-second) and count convolutions; if
         the conv-count x batch predictor says even ONE trial compile
         could be pathological on this toolchain, chunk by the predictor
         without probing further;
      2. otherwise trial-AOT-compile the lowered module (seconds on a
         healthy toolchain) and read XLA's own `memory_analysis()`: when
         peak temp bytes exceed the memory budget, chunk proportionally —
         bounding the conv-grad activation footprint that drives the
         memory-pressure/swap pathology at pixel batch sizes.
    """
    if backend is None:
        import jax

        backend = jax.default_backend()
    budget = compile_budget_s() if budget_s is None else budget_s
    mem_budget = (
        partition_mem_budget_bytes() if mem_budget_bytes is None else mem_budget_bytes
    )
    if backend != "cpu":
        return PartitionDecision(
            chunk=0, backend=backend, batch=batch, predicted_seconds=0.0,
            budget_s=budget, reason="non-cpu backend: keep fused",
        )
    if ledger_key is not None:
        decision = _decide_from_ledger(
            ledger_key, example, batch, budget, mem_budget, backend
        )
        if decision is not None:
            return decision

    # the MEASUREMENT (lowering + trial compile) is memoized in the unified
    # decision cache (compile/decisions.py, family `batch_chunk`, the same
    # store the scan-unroll ladder and the remat gate use): a repeat run at
    # the same (name, avals, jax version, backend) key skips every trial
    # compile. Only the measurement is cached — the CHUNK is re-derived
    # below from the budgets in force at call time, so a budget change
    # never serves a stale decision.
    from . import decisions as dec

    def _measure() -> dict:
        try:
            lowered = fn.lower(*avals_of(example))
            text = lowered.as_text()
        except Exception as err:
            return {"error": f"lowering failed: {type(err).__name__}"}
        rec: dict = {
            "counts": {
                "convolutions": text.count("stablehlo.convolution"),
                "dots": text.count("stablehlo.dot"),
                "ops": text.count(" = "),
            },
            "trial": False,
        }
        p = predicted_cpu_compile_seconds(rec["counts"]["convolutions"], batch)
        if p > budget * 10:
            # a toolchain with superlinear conv-grad compile would hang the
            # trial compile itself: decide on the predictor alone
            return rec
        try:
            t0 = _time.perf_counter()
            exe = lowered.compile()
            trial_s = _time.perf_counter() - t0
            ma = exe.memory_analysis()
            temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
        except Exception as err:
            rec["error"] = f"trial compile failed: {type(err).__name__}"
            return rec
        rec.update(trial=True, trial_seconds=trial_s, temp_bytes=temp)
        return rec

    probe_name = _probe_name(fn, ledger_key, batch)
    record, source = dec.measured_probe(
        "batch_chunk", probe_name, example, _measure, store_path=store_path
    )
    counts = dict(record.get("counts", {}))
    if record.get("error") and not counts:
        return PartitionDecision(
            chunk=0, backend=backend, batch=batch, predicted_seconds=0.0,
            budget_s=budget, reason=record["error"],
        )
    pred = predicted_cpu_compile_seconds(counts.get("convolutions", 0), batch)
    if not record.get("trial") and not record.get("error") and pred <= budget * 10:
        # cached under a larger budget that skipped the trial; this budget
        # wants the measured quantities — re-measure once
        record, source = dec.measured_probe(
            "batch_chunk", probe_name, example, _measure,
            store_path=store_path, force=True,
        )
        counts = dict(record.get("counts", {}))
    tag = " [probe cache]" if source == "cache" else ""
    if record.get("error"):
        return PartitionDecision(
            chunk=0, backend=backend, batch=batch, predicted_seconds=pred,
            budget_s=budget, counts=counts, reason=record["error"] + tag,
        )
    if not record.get("trial"):
        chunk = chunk_for_budget(batch, counts.get("convolutions", 0), budget)
        return PartitionDecision(
            chunk=chunk, backend=backend, batch=batch, predicted_seconds=pred,
            budget_s=budget, counts=counts,
            reason=(
                f"predicted {pred:.0f}s compile: chunk {batch} -> {chunk} "
                f"without trial compile{tag}"
            ),
        )
    trial_s = float(record["trial_seconds"])
    temp_bytes = int(record["temp_bytes"])
    counts["temp_bytes"] = temp_bytes
    counts["trial_compile_ms"] = int(trial_s * 1000)
    if trial_s > budget:
        chunk = _chunk_for_ratio(batch, budget / trial_s)
        reason = (
            f"trial compile {trial_s:.0f}s > budget {budget:.0f}s: "
            f"chunk {batch} -> {chunk}"
        )
    elif temp_bytes > mem_budget:
        chunk = _chunk_for_ratio(batch, mem_budget / temp_bytes)
        reason = (
            f"peak temp {temp_bytes / 2**20:.0f}MiB > budget "
            f"{mem_budget / 2**20:.0f}MiB: chunk {batch} -> {chunk}"
        )
    else:
        chunk = 0
        reason = (
            f"compile {trial_s:.1f}s and peak temp "
            f"{temp_bytes / 2**20:.0f}MiB within budget"
        )
    if chunk >= batch:
        chunk = 0
    return PartitionDecision(
        chunk=chunk, backend=backend, batch=batch, predicted_seconds=pred,
        budget_s=budget, counts=counts, reason=reason + tag,
    )


def _probe_name(fn: Callable, ledger_key: str | None, batch: int) -> str:
    """A stable per-jit probe name for the decision cache: the ledger key
    when the caller has one, else the function's qualified name (locally
    defined probes stay distinct through `<locals>`)."""
    if ledger_key:
        base = ledger_key
    else:
        base = (
            f"{getattr(fn, '__module__', '')}."
            f"{getattr(fn, '__qualname__', getattr(fn, '__name__', 'fn'))}"
        )
    return f"{base}[batch={batch}]"


def _decide_from_ledger(
    ledger_key: str,
    example: tuple,
    batch: int,
    budget: float,
    mem_budget: int,
    backend: str,
) -> PartitionDecision | None:
    """Byte-driven partition decision from the committed sheepmem ledger
    (decision-ladder step 0). None when the ledger has no usable entry —
    the caller falls back to the measured lower/trial-compile ladder.

    The ledger's temp bytes were measured at the tiny capture avals; the
    live config's footprint is predicted by scaling with the argument-byte
    ratio (activations scale with the data, parameters cancel out of the
    ratio). The conv predictor cross-validates from the committed
    primitive histogram in the same spec file's `jits` section; the chunk
    honors whichever constraint is tighter."""
    mem = ledger_entry(ledger_key, "memory")
    if not mem or not mem.get("argument_bytes"):
        return None
    try:
        live_args = _example_arg_bytes(example)
    except Exception:
        return None
    ratio = max(live_args / max(int(mem["argument_bytes"]), 1), 1.0)
    predicted_temp = int(int(mem.get("temp_bytes", 0)) * ratio)
    jits = ledger_entry(ledger_key, "jits") or {}
    convs = int(jits.get("primitives", {}).get("conv_general_dilated", 0))
    pred_s = predicted_cpu_compile_seconds(convs, batch)
    counts = {
        "ledger_temp_bytes": int(mem.get("temp_bytes", 0)),
        "ledger_argument_bytes": int(mem["argument_bytes"]),
        "live_argument_bytes": live_args,
        "predicted_temp_bytes": predicted_temp,
        "convolutions": convs,
    }
    candidates = []
    if predicted_temp > mem_budget:
        candidates.append(_chunk_for_ratio(batch, mem_budget / predicted_temp))
    if pred_s > budget:
        candidates.append(chunk_for_budget(batch, convs, budget) or 1)
    chunk = min((c for c in candidates if c), default=0)
    if chunk >= batch:
        chunk = 0
    if chunk:
        reason = (
            f"ledger {ledger_key}: predicted temp "
            f"{predicted_temp / 2**20:.0f}MiB vs budget "
            f"{mem_budget / 2**20:.0f}MiB (predictor {pred_s:.0f}s): "
            f"chunk {batch} -> {chunk}"
        )
    else:
        reason = (
            f"ledger {ledger_key}: predicted temp "
            f"{predicted_temp / 2**20:.1f}MiB and predictor {pred_s:.0f}s "
            "within budget"
        )
    return PartitionDecision(
        chunk=chunk, backend=backend, batch=batch, predicted_seconds=pred_s,
        budget_s=budget, counts=counts, reason=reason,
    )
