"""CompilePlan: AOT shape-capture + background warm-start compilation.

The ISSUE 5 tentpole. XLA compile latency is the dominant startup cost of
every algorithm task (the full-scale DreamerV3 step is ~30-40 s per config
on TPU and ~30 s even at debug widths on XLA:CPU — graph complexity, not
width, drives it), and the off-policy tasks all spend their
`learning_starts` window collecting random actions — dead time in which
the update executables could already be compiling. Podracer
(arXiv:2104.06272) keeps the chip busy through exactly these
startup/handoff windows; MSRL (arXiv:2210.00882) treats the training
program as schedulable fragments. This module does the minimal JAX-native
version of both:

  1. **shape capture** — each algo main registers its hot jits (train step,
     player policy, GAE, recon, imagination) together with a zero-cost
     *example thunk* producing their exact call arguments (live pytrees
     and/or `jax.ShapeDtypeStruct` specs);
  2. **AOT compile** — `jit.lower(*avals).compile()` builds the executable
     without executing anything;
  3. **background warm start** — worker threads run the AOT compiles
     concurrently with env collection (`--warm_compile on`); the returned
     wrapper is the **barrier**: its first call blocks until that entry's
     compile finishes, then dispatches the AOT executable directly. XLA
     compilation releases the GIL, so collection and compilation genuinely
     overlap on one process — fully on multi-core hosts, and inside the
     env-latency windows (real-time envs) even on a single core.

`SHEEPRL_TPU_WARM_MODE=warmup` swaps step 2-3 for a background warmup
call on synthesized dummy zeros: the executable lands in the jit's own
dispatch cache (it IS the cold-path executable, and this dodges a measured
~1.7x AOT-vs-dispatch compile penalty on XLA:CPU) at the price of
executing one dummy update — use where execution is cheap vs compile.

Equivalence guarantee: the AOT path lowers the SAME jitted callable at the
SAME input avals the live call would, so the compiled program is identical
to the cold-path one and results are bit-exact vs `--warm_compile off`
(tests/test_compile/test_plan.py). Any aval mismatch at call time (shape
drift, weak-type flip, resharded input) falls back to the original jitted
callable — warm start can only lose its head start, never change results.

Observability: per-executable compile seconds and persistent-cache hit/miss
counts surface as `Compile/*` gauges (registered with the run's Telemetry)
plus `compile` events in telemetry.jsonl, and the plan stamps
`Compile/time_to_first_update_seconds` — the headline `bench.py
--algo warm_compile` prices — when the first `role="update"` call returns.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from typing import Any, Callable

from .cache import CacheStats

__all__ = [
    "CaptureComplete",
    "CompilePlan",
    "DataEdge",
    "WarmJit",
    "avals_of",
    "sds",
]


class CaptureComplete(BaseException):
    """Raised by `CompilePlan.start()` in capture mode
    (`SHEEPRL_TPU_PLAN_MODE=capture`): unwinds the algo main at the exact
    point where the training loop would begin — every hot jit is registered
    with its example thunk, nothing has executed — carrying the plan to the
    caller (tools/sheepcheck.py). BaseException on purpose: a stray
    `except Exception` in a main must not swallow the unwind."""

    def __init__(self, plan: "CompilePlan"):
        super().__init__("compile plan captured (SHEEPRL_TPU_PLAN_MODE=capture)")
        self.plan = plan


def sds(shape, dtype, sharding=None):
    """Shorthand for `jax.ShapeDtypeStruct` (the shape-capture spec leaf)."""
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)


def avals_of(tree: Any) -> Any:
    """Map a pytree of arrays to ShapeDtypeStructs. COMMITTED jax.Arrays
    (device_put with an explicit sharding/device — replicated train states,
    trainer-mesh batches, player-device obs) keep their sharding so the AOT
    executable is built for the layout the live call uses; uncommitted
    arrays (fresh `jnp.asarray` puts, PRNG keys) stay sharding-free —
    capturing their incidental device-0 placement would make the lowering
    reject mixed-device calls the live jit resolves fine. Non-array leaves
    (python scalars, None, specs) pass through untouched — `lower()` treats
    them exactly as a live call would, weak types included."""
    import jax
    import numpy as np

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        if isinstance(x, jax.Array):
            sharding = x.sharding if getattr(x, "_committed", False) else None
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        if isinstance(x, np.ndarray):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x

    return jax.tree_util.tree_map(one, tree)


class DataEdge:
    """A declared producer->consumer contract between two registered jits:
    "(some of) `src`'s outputs become `dst`'s inputs". The sheepshard
    analyzer (analysis/shard_check.py) resolves both ends to their
    compiled SPMD shardings and checks the contract:

      - `expect="match"`: the data flows device-to-device with no host
        reshuffle in between (the Anakin rollout->gae path), so the
        producer's output sharding and the consumer's input sharding must
        agree — a disagreement forces an implicit reshard (all-gather +
        re-slice) on EVERY handoff (rule SC008);
      - `expect="reshard"`: the main reshuffles the data on purpose between
        the two jits (host reshape + shard_batch, a replay ring, a
        decoupled to_trainers put), so a sharding change across the edge is
        the documented contract; the resolved pair is still recorded in
        the comms ledger so drift stays visible.

    `pairs` optionally names exact (src_output_index, dst_input_index)
    flat positions; when None the analyzer matches outputs to inputs by
    (shape, dtype) groups. This is the first concrete slice of the
    ROADMAP-4 fragment graph: the data edges of the fragment dataflow,
    declared once per main, machine-checkable."""

    __slots__ = ("src", "dst", "pairs", "expect", "note")

    def __init__(
        self,
        src: str,
        dst: str,
        pairs: list[tuple[int, int]] | None = None,
        expect: str = "match",
        note: str | None = None,
    ):
        if expect not in ("match", "reshard"):
            raise ValueError(f"expect must be 'match' or 'reshard', got {expect!r}")
        self.src = src
        self.dst = dst
        self.pairs = pairs
        self.expect = expect
        self.note = note

    @property
    def key(self) -> str:
        return f"{self.src}->{self.dst}"


class _Entry:
    __slots__ = (
        "name", "fn", "example", "role", "executable", "compile_seconds",
        "cache_hits", "cache_misses", "error", "done", "aot_calls",
        "fallbacks", "barrier_wait_s", "warmed", "memory",
    )

    def __init__(self, name: str, fn: Callable, example: Callable | None, role: str | None):
        self.name = name
        self.fn = fn
        self.example = example
        self.role = role
        self.executable: Any = None
        self.compile_seconds = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self.error: str | None = None
        self.done = threading.Event()
        self.aot_calls = 0
        self.fallbacks = 0
        self.barrier_wait_s = 0.0
        self.warmed = False
        self.memory: dict | None = None  # memory_analysis of the AOT exe


def _materialize(specs: Any) -> Any:
    """Dummy call arguments for warmup mode: zeros for every captured aval
    (device_put to the captured sharding when committed); non-spec leaves
    (python scalars) pass through."""
    import jax
    import jax.numpy as jnp

    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            z = jnp.zeros(x.shape, x.dtype)
            if x.sharding is not None:
                z = jax.device_put(z, x.sharding)
            return z
        return x

    return jax.tree_util.tree_map(one, specs)


class WarmJit:
    """The callable a main uses in place of its raw jit. Dispatch policy:

    - warm start running and this entry not compiled yet -> BLOCK (the
      barrier before the first update);
    - AOT executable available -> call it directly (no retrace, no
      dispatch-cache miss);
    - no executable (warm off, unsupported fn, compile error, or a prior
      aval mismatch) -> call the original jitted fn.

    Also the `time_to_first_update_seconds` probe: the first completed call
    of a `role="update"` entry stamps the plan, warm or cold alike.
    """

    __slots__ = ("_entry", "_plan")

    def __init__(self, entry: _Entry, plan: "CompilePlan"):
        self._entry = entry
        self._plan = plan

    @property
    def fn(self) -> Callable:
        """The underlying jitted callable (escape hatch for introspection)."""
        return self._entry.fn

    def __call__(self, *args, **kwargs):
        e = self._entry
        plan = self._plan
        if plan._started and not e.done.is_set():
            t0 = time.perf_counter()
            e.done.wait()
            e.barrier_wait_s += time.perf_counter() - t0
        exe = e.executable
        if exe is not None and not kwargs:
            try:
                out = exe(*args)
                e.aot_calls += 1
            except Exception as err:  # aval/sharding drift: fall back for good
                e.executable = None
                e.fallbacks += 1
                plan._event(
                    "compile",
                    jit=e.name,
                    mode="aot_fallback",
                    error=f"{type(err).__name__}: {err}"[:300],
                )
                out = e.fn(*args, **kwargs)
        else:
            out = e.fn(*args, **kwargs)
        if e.role == "update" and plan._first_update_s is None:
            plan._note_first_update()
        return out


class CompilePlan:
    """Registry of a run's hot jits + the background warm-start engine.

    Wiring (every algo main):

        plan = CompilePlan.from_args(args, telem)
        telem.add_gauges(plan.gauges)
        ...
        train_step = plan.register("train_step", train_step,
                                   example=lambda: (state, data_spec, key, flag),
                                   role="update")
        policy_step = plan.register("policy_step", policy_step,
                                    example=lambda: (actor, obs_spec, key))
        plan.start()          # overlaps with the learning_starts collection
        ... training loop unchanged (first update blocks on the barrier) ...
        plan.close()

    With `--warm_compile off` the wrappers are pass-throughs (plus the
    first-update stamp) and `start()` is a no-op — the cold path is the
    exact seed behavior.
    """

    def __init__(
        self,
        enabled: bool = False,
        telem: Any = None,
        threads: int | None = None,
        capture_only: bool = False,
    ):
        self.enabled = enabled
        # capture mode (sheepcheck): record EVERY register() with its example
        # thunk regardless of --warm_compile, compile nothing, and raise
        # CaptureComplete from start() so the main never runs a step
        self.capture_only = capture_only
        self._telem = telem
        self._threads = threads
        self._entries: list[_Entry] = []
        self._edges: list[DataEdge] = []
        self._lock = threading.Lock()
        self._started = False
        self._closed = False
        self._t0 = time.perf_counter()
        self._first_update_s: float | None = None
        self._workers: list[threading.Thread] = []
        self._queue: list[_Entry] = []
        self._cache_stats = CacheStats()

    @classmethod
    def from_args(cls, args: Any, telem: Any = None) -> "CompilePlan":
        capture_only = os.environ.get("SHEEPRL_TPU_PLAN_MODE") == "capture"
        enabled = getattr(args, "warm_compile", "off") == "on" and not capture_only
        threads = int(os.environ.get("SHEEPRL_TPU_WARM_THREADS", "0")) or None
        return cls(
            enabled=enabled, telem=telem, threads=threads, capture_only=capture_only
        )

    # ---- registration ------------------------------------------------------
    def register(
        self,
        name: str,
        fn: Callable,
        example: Callable[[], tuple] | None = None,
        role: str | None = None,
    ) -> Callable:
        """Register a jitted callable with a thunk producing its exact call
        arguments (live pytrees / ShapeDtypeStructs; evaluated lazily in the
        compile worker). Returns the callable the main should use in place
        of `fn`. A fn without `.lower` (e.g. a checkify wrapper) or without
        an example is tracked for first-update timing only."""
        if self.capture_only:
            # shape capture: keep the raw entry (fn + example thunk) for
            # sheepcheck's abstract eval; the main keeps its plain callable
            # (it never runs — start() raises CaptureComplete)
            entry = _Entry(name, fn, example, role)
            entry.done.set()
            with self._lock:
                self._entries.append(entry)
            return fn
        if not self.enabled and role is None:
            return fn
        entry = _Entry(name, fn, example, role)
        if not self.enabled or example is None or not hasattr(fn, "lower"):
            if self.enabled and example is not None:
                entry.error = "not AOT-lowerable"
            entry.done.set()
        with self._lock:
            self._entries.append(entry)
        return WarmJit(entry, self)

    def declare_edge(
        self,
        src: str,
        dst: str,
        pairs: list[tuple[int, int]] | None = None,
        expect: str = "match",
        note: str | None = None,
    ) -> None:
        """Declare that (some of) `src`'s outputs feed `dst`'s inputs — the
        cross-jit dataflow contract sheepshard's SC008 checks against the
        compiled SPMD shardings (see DataEdge). Zero-cost at runtime:
        edges are metadata, recorded in every plan mode."""
        with self._lock:
            self._edges.append(DataEdge(src, dst, pairs=pairs, expect=expect, note=note))

    @property
    def edges(self) -> list[DataEdge]:
        return list(self._edges)

    # ---- background compilation -------------------------------------------
    def start(self) -> None:
        """Kick off the AOT compiles. Call after the last register() and
        before the collection loop; idempotent; warm-off plans only re-anchor
        the first-update clock.

        `time_to_first_update_seconds` anchors HERE (not at construction):
        the metric prices the collect-then-compile critical path the warm
        start attacks, so it starts when collection starts — process setup
        (env build, buffer alloc, init-time mini-compiles) is identical in
        both arms and outside the subsystem's control."""
        if self._started:
            return
        if self.capture_only:
            self._started = True
            raise CaptureComplete(self)
        self._t0 = time.perf_counter()
        if not self.enabled:
            self._started = True
            return
        self._cache_stats.attach()
        # a run that dies (or returns) without plan.close() must still join
        # the compile workers: a daemon thread mid-XLA-compile at interpreter
        # teardown aborts the process (`terminate called without an active
        # exception`) — the registered-but-never-called-jit exit abort
        atexit.register(self.close)
        with self._lock:
            self._queue = [e for e in self._entries if not e.done.is_set()]
            # interaction jits (player/policy/gae) are needed from the FIRST
            # collection step; the update jits only at the training barrier.
            # Compile the cheap interaction entries first so the rollout
            # never queues behind a long train-step compile.
            self._queue.sort(key=lambda e: e.role == "update")
            n = min(
                self._threads or 1,
                max(len(self._queue), 1),
            )
        self._started = True
        for i in range(n):
            t = threading.Thread(
                target=self._worker, name=f"warm-compile-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)

    def _worker(self) -> None:
        while True:
            with self._lock:
                if not self._queue or self._closed:
                    return
                entry = self._queue.pop(0)
            self._compile_entry(entry)

    def _compile_entry(self, e: _Entry) -> None:
        import jax  # noqa: F401  (worker threads need jax initialized)

        # SHEEPRL_TPU_WARM_MODE=warmup switches the engine from AOT
        # (`lower().compile()`, executes nothing, returns a Compiled the
        # wrapper dispatches directly) to a background WARMUP CALL: dummy
        # zeros are synthesized from the captured avals (respecting any
        # committed shardings) and `fn` is called once, outputs discarded —
        # the executable lands in the jit's own dispatch cache, so the main
        # thread's first real call is a pure cache hit. Warmup is the
        # stronger equivalence (the cached executable IS the cold-path one,
        # and it dodges the measured ~1.7x AOT compile penalty on XLA:CPU)
        # but it EXECUTES one dummy update — only worth it where execution
        # is cheap relative to compile. Donation is safe either way: the
        # donated buffers are the synthesized dummies.
        warmup = os.environ.get("SHEEPRL_TPU_WARM_MODE") == "warmup"
        before = self._cache_stats.snapshot()
        t0 = time.perf_counter()
        try:
            args = e.example()
            specs = avals_of(args)
            if warmup:
                dummies = _materialize(specs)
                jax.block_until_ready(e.fn(*dummies))
                e.warmed = True
            else:
                e.executable = e.fn.lower(*specs).compile()
                # the ISSUE-10 memory-capture hook: every AOT executable
                # reports its static footprint (the runtime half of the
                # sheepmem ledger — telemetry_report compares the two)
                from .partition import compiled_memory_stats

                e.memory = compiled_memory_stats(e.executable)
        except Exception as err:
            e.error = f"{type(err).__name__}: {err}"[:300]
        e.compile_seconds = time.perf_counter() - t0
        after = self._cache_stats.snapshot()
        # with the default single worker these deltas attribute exactly;
        # with SHEEPRL_TPU_WARM_THREADS>1 concurrent compiles share them
        e.cache_hits = after["hits"] - before["hits"]
        e.cache_misses = after["misses"] - before["misses"]
        e.done.set()
        self._event(
            "compile",
            jit=e.name,
            mode="warmup" if warmup else "warm",
            seconds=round(e.compile_seconds, 3),
            cache_hits=e.cache_hits,
            cache_misses=e.cache_misses,
            error=e.error,
        )

    def wait(self, timeout: float | None = None) -> bool:
        """Explicit barrier over every registered entry (the per-call
        barrier in WarmJit usually makes this unnecessary)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        for e in list(self._entries):
            left = None if deadline is None else max(deadline - time.monotonic(), 0.0)
            if not e.done.wait(left):
                return False
        return True

    # ---- observability -----------------------------------------------------
    def _event(self, name: str, **data: Any) -> None:
        if self._telem is not None:
            try:
                self._telem.event(name, **data)
            # sheeplint: disable=SL012 — same contract as the sanitizer: the
            # event sink itself is the thing that failed
            except Exception:
                pass  # telemetry must never kill the compile path

    def _note_first_update(self) -> None:
        with self._lock:
            if self._first_update_s is not None:
                return
            self._first_update_s = time.perf_counter() - self._t0
        self._event(
            "first_update",
            seconds=round(self._first_update_s, 3),
            warm_compile="on" if self.enabled else "off",
        )

    @property
    def time_to_first_update_seconds(self) -> float | None:
        return self._first_update_s

    def stats(self) -> dict[str, Any]:
        entries = list(self._entries)
        return {
            "enabled": self.enabled,
            "entries": {
                e.name: {
                    "compiled": e.executable is not None or e.warmed,
                    "warmed": e.warmed,
                    "compile_seconds": e.compile_seconds,
                    "cache_hits": e.cache_hits,
                    "cache_misses": e.cache_misses,
                    "aot_calls": e.aot_calls,
                    "fallbacks": e.fallbacks,
                    "error": e.error,
                    "memory": e.memory,
                }
                for e in entries
            },
            "time_to_first_update_seconds": self._first_update_s,
        }

    def gauges(self) -> dict[str, float]:
        """`Compile/*` gauge source for Telemetry.add_gauges."""
        entries = list(self._entries)
        out = {
            "Compile/warm_enabled": float(self.enabled),
            "Compile/plan_entries": float(len(entries)),
            "Compile/plan_compiled": float(
                sum(1 for e in entries if e.executable is not None or e.warmed)
            ),
            "Compile/warm_compile_seconds": sum(e.compile_seconds for e in entries),
            "Compile/cache_hits": float(sum(e.cache_hits for e in entries)),
            "Compile/cache_misses": float(sum(e.cache_misses for e in entries)),
            "Compile/aot_calls": float(sum(e.aot_calls for e in entries)),
            "Compile/aot_fallbacks": float(sum(e.fallbacks for e in entries)),
            "Compile/barrier_wait_seconds": sum(e.barrier_wait_s for e in entries),
        }
        for e in entries:
            if e.compile_seconds:
                out[f"Compile/exe/{e.name}_seconds"] = e.compile_seconds
            if e.memory is not None:
                out[f"Compile/exe/{e.name}_peak_bytes"] = float(
                    e.memory["peak_bytes"]
                )
        peaks = [e.memory["peak_bytes"] for e in entries if e.memory is not None]
        if peaks:
            out["Compile/plan_peak_bytes_max"] = float(max(peaks))
        if self._first_update_s is not None:
            out["Compile/time_to_first_update_seconds"] = self._first_update_s
        return out

    # ---- lifecycle ---------------------------------------------------------
    def close(self, join_timeout: float | None = None) -> None:
        """End-of-run teardown: cancel queued compiles, join the workers
        (bounded), emit the summary event, detach listeners.

        The join is the exit-abort fix: a WarmJit whose jit is never called
        never waits on its entry, so a run could reach interpreter teardown
        with a worker daemon thread still inside an XLA compile — which
        aborts the process with `terminate called without an active
        exception`. Cancelling the queue bounds the wait to the ONE compile
        already in flight; the join waits for it up to
        `SHEEPRL_TPU_WARM_JOIN_S` (default 120 s — every measured XLA:CPU
        compile in this repo is well under that). `start()` wires this to
        `atexit` so even an exception path gets the join."""
        if self._closed:
            return
        self._closed = True
        try:
            atexit.unregister(self.close)
        # sheeplint: disable=SL012 — unregister of an already-drained atexit
        # hook during interpreter teardown; nothing to record, nowhere to
        # record it
        except Exception:
            pass
        # cancel entries the workers have not picked up yet; their barrier
        # waiters (if any raced close) fall back to the cold jitted fn
        with self._lock:
            cancelled, self._queue = self._queue, []
        for e in cancelled:
            if not e.done.is_set():
                e.error = e.error or "cancelled: plan closed before compile started"
                e.done.set()
        if join_timeout is None:
            try:
                join_timeout = float(os.environ.get("SHEEPRL_TPU_WARM_JOIN_S", "120"))
            except ValueError:
                join_timeout = 120.0
        deadline = time.monotonic() + max(join_timeout, 0.0)
        for t in self._workers:
            t.join(max(deadline - time.monotonic(), 0.0))
        self._cache_stats.detach()
        if self.enabled or self._first_update_s is not None:
            self._event("compile.summary", **_jsonable(self.stats()))


def _jsonable(d: dict) -> dict:
    import json

    try:
        json.dumps(d)
        return d
    except (TypeError, ValueError):
        return {"repr": repr(d)[:1000]}
