"""Shared shape-capture spec builders for the algo mains.

The Dreamer family (dreamer_v1/v2/v3, p2e_dv1/dv2) all train on `[T, B]`
sequential replay samples with the same key layout (dict obs + one-hot/
continuous actions + scalar channels), so the CompilePlan example spec is
built once here instead of five times inline. Off-policy/on-policy mains
with simpler batches build their specs inline.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

from .plan import sds

__all__ = ["dreamer_sample_spec", "dict_obs_spec"]


def dict_obs_spec(obs_space: Any, keys: Sequence[str], cnn_keys: Sequence[str], lead: tuple):
    """Spec of a dict observation put (`{k: jnp.asarray(obs[k])}`): uint8
    pixels, float32 vectors (x64 is disabled on device, so float64 spaces
    land as f32)."""
    import jax.numpy as jnp

    return {
        k: sds(
            lead + tuple(obs_space[k].shape),
            jnp.uint8 if k in cnn_keys else jnp.float32,
        )
        for k in keys
    }


def dreamer_sample_spec(
    obs_space: Any,
    obs_keys: Sequence[str],
    cnn_keys: Sequence[str],
    T: int,
    B: int,
    act_sum: int,
    extra: Iterable[str] = ("rewards", "dones"),
    mesh: Any = None,
) -> dict:
    """`[T, B, ...]` spec of one sequential replay sample — the Dreamer
    train-step batch. With a multi-device mesh the leaves carry the
    time/batch sharding `shard_time_batch` would apply."""
    import jax.numpy as jnp

    sharding = None
    if mesh is not None and mesh.devices.size > 1:
        from ..parallel.mesh import time_batch_sharding

        sharding = time_batch_sharding(mesh)
    spec = {}
    for k in obs_keys:
        dt = jnp.uint8 if k in cnn_keys else jnp.float32
        spec[k] = sds((T, B) + tuple(obs_space[k].shape), dt, sharding=sharding)
    spec["actions"] = sds((T, B, act_sum), jnp.float32, sharding=sharding)
    for k in extra:
        spec[k] = sds((T, B, 1), jnp.float32, sharding=sharding)
    return spec
