"""Runtime transfer/donation sanitizer (`--sanitize`), the dynamic half of
sheeplint.

The linter proves the *code* cannot host-sync inside a trace; the sanitizer
proves the *run* does not smuggle implicit host<->device transfers into
phases that must be device-only, and that the train step's arithmetic stays
finite. Two mechanisms, both off unless `--sanitize` is passed (zero
overhead otherwise):

  - transfer guard: `checked(phase, fn, ...)` runs `fn` under
    `jax.transfer_guard("disallow")`. An implicit transfer raises inside
    XLA; the wrapper records it (first occurrence per phase emits a
    `sanitizer.transfer` telemetry event with the guard message), then
    RERUNS the call unguarded so training continues — sanitize mode audits,
    it does not crash the run.
  - checkify: `checkified(fn)` wraps a train step with
    `checkify.checkify(..., errors=float_checks)` under jit; after each
    call the error payload is consumed and any NaN/div finding emits a
    `sanitizer.checkify` event.

Violation counts ride the normal metric pipeline via `gauges()`
(`Sanitizer/...` keys), so tensorboard and telemetry.jsonl both show them.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["Sanitizer"]


class Sanitizer:
    def __init__(self, enabled: bool = False, telemetry: Any = None):
        self.enabled = enabled
        self.telemetry = telemetry
        # (phase, kind) -> count; kinds: "transfer", "checkify"
        self.counts: dict[tuple[str, str], int] = {}
        if enabled:
            self._emit(
                "sanitizer.start",
                transfer_guard="disallow (guarded phases)",
                checkify="float_checks (nan + div)",
            )

    @classmethod
    def from_args(cls, args: Any, telemetry: Any = None) -> "Sanitizer":
        """Construction helper mirroring Telemetry.from_args: reads the
        StandardArgs `sanitize` flag every algo parser now carries."""
        return cls(bool(getattr(args, "sanitize", False)), telemetry)

    # ---- plumbing ---------------------------------------------------------
    def _emit(self, event: str, **data: Any) -> None:
        if self.telemetry is not None:
            try:
                self.telemetry.event(event, **data)
            # sheeplint: disable=SL012 — the sanitizer reports THROUGH telemetry;
            # a broken telemetry sink has nowhere better to report to
            except Exception:
                pass

    def _record(self, phase: str, kind: str, message: str) -> None:
        key = (phase, kind)
        first = key not in self.counts
        self.counts[key] = self.counts.get(key, 0) + 1
        if first:
            self._emit(
                f"sanitizer.{kind}", phase=phase, message=message[:500]
            )

    def gauges(self) -> dict[str, float]:
        """Interval-merged counters (register with telem.add_gauges)."""
        if not self.enabled:
            return {}
        out = {
            f"Sanitizer/{kind}_{phase}": float(n)
            for (phase, kind), n in self.counts.items()
        }
        out["Sanitizer/enabled"] = 1.0
        return out

    # ---- transfer guard ---------------------------------------------------
    def checked(self, phase: str, fn: Callable, *args: Any, **kwargs: Any):
        """Run `fn` under transfer_guard("disallow"); on an implicit-transfer
        trip, record it and rerun unguarded (audit, don't crash)."""
        if not self.enabled:
            return fn(*args, **kwargs)
        import jax

        try:
            with jax.transfer_guard("disallow"):
                return fn(*args, **kwargs)
        except Exception as exc:
            message = str(exc)
            if "transfer" not in message.lower():
                raise
            self._record(phase, "transfer", message.splitlines()[0])
            return fn(*args, **kwargs)

    # ---- checkify ---------------------------------------------------------
    def checkified(
        self,
        fn: Callable,
        *,
        phase: str = "train",
        jit: Optional[Callable] = None,
    ) -> Callable:
        """Wrap `fn` with checkify float checks under jit; the wrapper keeps
        `fn`'s signature and return value, consuming the error channel into
        telemetry. `jit` overrides the jit transform (default jax.jit —
        donation is intentionally dropped: the checkify error args shift
        argnums, and sanitize runs are audits, not perf runs)."""
        if not self.enabled:
            raise RuntimeError("checkified() requires an enabled Sanitizer")
        import jax
        from jax.experimental import checkify

        checked = (jit or jax.jit)(
            checkify.checkify(fn, errors=checkify.float_checks)
        )
        # visible proof in telemetry.jsonl that the run's train step carried
        # float checks, even when it never trips
        self._emit("sanitizer.checkify_armed", phase=phase)

        def wrapper(*args: Any, **kwargs: Any):
            err, out = checked(*args, **kwargs)
            msg = err.get()
            if msg:
                self._record(phase, "checkify", msg)
            return out

        return wrapper

    def close(self) -> None:
        """Emit the end-of-run violation summary event."""
        if not self.enabled:
            return
        self._emit(
            "sanitizer.summary",
            counts={
                f"{kind}:{phase}": n for (phase, kind), n in self.counts.items()
            },
            clean=not self.counts,
        )
