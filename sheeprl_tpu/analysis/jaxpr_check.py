"""sheepcheck: jaxpr-level whole-program analysis over the CompilePlan.

sheeplint (linter.py) proves hazards from SOURCE — it never sees through a
`jax.jit` boundary, a helper defined in another module, or anything that
only materializes in the traced program. Since PR 5 every hot jit of all 13
algo mains is registered in the CompilePlan with an example thunk producing
its exact input avals, and PR 6 made whole rollouts single jits — so the
program we actually dispatch is fully described by that registry, the way
MSRL's dataflow fragments describe a training job as an analyzable graph
(arXiv:2210.00882). This module closes the loop: instantiate a main's plan
in capture mode (`SHEEPRL_TPU_PLAN_MODE=capture` — CPU, tiny avals, zero
execution), abstract-eval each registered jit to a ClosedJaxpr via
`jit.trace(*avals)`, and run IR-level analyzers over it. Podracer-style
fully-jitted loops (arXiv:2104.06272) make exactly these hazards invisible
to AST linting: a dtype upcast, a host callback, or a dead donation inside
a `lax.scan` body is a property of the traced program, not of any one
source file.

Rule catalog (SC = sheepcheck; suppressions live in `SUPPRESSIONS` below,
keyed `(algo, jit, rule)`, each with a mandatory justification):

  SC001  silent dtype promotion — any float64 value, or a widening float
         `convert_element_type` (f32->f64 always; bf16->f32 only under
         `audit_bf16=True`, the ROADMAP-5c mixed-precision audit: a
         bf16 model whose jaxpr silently upcasts to f32 pays full-width
         FLOPs while claiming bf16).
  SC002  host callback / infeed / outfeed traced into the jit — pure/io/
         debug callbacks serialize the program on a host round-trip per
         dispatch (jax.debug.print left in a scan body is the classic).
  SC003  donation hazards — a donated argument aliased into >=2 outputs,
         donated but dead (unused in the jaxpr), or donated with no
         shape/dtype-compatible output to reuse its buffer (XLA drops the
         alias: the donation silently buys nothing).
  SC004  weak-type hazards — weak-typed scan-carry avals (the carry
         fixpoint retraces the body once per weak leaf, and any
         strong-typed caller of the same program retraces the whole jit),
         weak-typed top-level jit inputs (a python scalar at the call
         site: retrace on weak/strong mix + an implicit h2d put per call),
         or carry/output aval mismatches.
  SC005  conv work above the measured XLA:CPU pathology threshold — the
         conv-count x batch predictor from compile/partition.py says this
         jit lands in the transposed-conv-grad regime `--split_update
         auto` / `--recon_chunk` exist for.

Each analyzable jit also yields a *fingerprint* — primitive histogram, op
count, dtype set, donation map, FLOP/byte estimates from XLA's
`cost_analysis` — which `tools/sheepcheck.py` writes to the committed
`analysis/budget/` ledger. CI re-derives the fingerprints and fails on
unexplained drift (new dtypes, op-count growth past tolerance, lost
donations): "did this PR quietly bloat or de-optimize a jit?" becomes a
gated check instead of a bench regression three rounds later.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Iterable, Iterator

from .rules import Rule

__all__ = [
    "SC_RULES",
    "SUPPRESSIONS",
    "CAPTURE_ARGV",
    "CAPTURE_VARIANTS",
    "resolve_capture",
    "Finding",
    "JitReport",
    "analyze_closed_jaxpr",
    "analyze_entry",
    "analyze_plan",
    "budget_dir_of",
    "budget_exists",
    "build_budget",
    "capture_plan",
    "check_budget",
    "declares_bf16",
    "fingerprint_jaxpr",
    "iter_eqns",
    "load_budget",
    "save_budget",
]

ERROR = "error"
WARNING = "warning"

_SC_RULES = [
    Rule(
        id="SC001",
        name="silent-dtype-promotion",
        severity=ERROR,
        summary=(
            "float64 value or widening float convert_element_type in the "
            "traced program (f32->f64 always; bf16->f32 under the "
            "mixed-precision audit) — double-width FLOPs and memory the "
            "source never asked for"
        ),
        autofix=(
            "pin dtypes at the boundary (jnp.float32(...)/astype), keep "
            "x64 disabled, and for bf16 paths cast moments/reductions "
            "explicitly so the audit sees intended upcasts only"
        ),
    ),
    Rule(
        id="SC002",
        name="host-callback-in-jit",
        severity=ERROR,
        summary=(
            "host callback (pure_callback/io_callback/debug_callback) or "
            "infeed/outfeed traced into a registered jit — every dispatch "
            "pays a host round-trip, and inside scan it serializes the "
            "whole rollout"
        ),
        autofix=(
            "remove the debug.print/io_callback from the hot jit (use "
            "telemetry gauges off-path), or suppress with justification "
            "for intentional instrumentation builds"
        ),
    ),
    Rule(
        id="SC003",
        name="donation-alias-conflict",
        severity=WARNING,
        summary=(
            "donated argument aliased into multiple outputs, dead in the "
            "jaxpr, or without any shape/dtype-matching output — XLA "
            "either rejects the alias or silently drops it, so the "
            "donation buys no buffer reuse"
        ),
        autofix=(
            "donate only arguments whose buffers a same-aval output can "
            "reuse (the train-state in, train-state out pattern); drop "
            "donate_argnums for pure readers"
        ),
    ),
    Rule(
        id="SC004",
        name="weak-type-instability",
        severity=WARNING,
        summary=(
            "weak-typed avals in positions that force extra traces: a "
            "lax.scan carry (the carry fixpoint retraces the body) or a "
            "top-level jit input (a python scalar at the call site — "
            "mixing weak/strong callers retraces the whole jit, and every "
            "call pays an implicit h2d put of the constant; the PR-2 "
            "gamma/lambda class), or a carry/output aval mismatch"
        ),
        autofix=(
            "initialize carries and call-site scalars with concrete-dtype "
            "arrays (jnp.float32(0.0), jnp.zeros(..., dtype)) instead of "
            "python scalars"
        ),
    ),
    Rule(
        id="SC005",
        name="cpu-conv-pathology",
        severity=WARNING,
        summary=(
            "convolution work above the measured XLA:CPU pathology "
            "threshold (conv-count x batch predictor, "
            "compile/partition.py) — transposed-conv-grad execution in "
            "this regime runs minutes-per-update on CPU"
        ),
        autofix=(
            "run the jit through decide_batch_chunk / --split_update auto "
            "/ --recon_chunk, or suppress where the jit only ever runs "
            "on TPU"
        ),
    ),
]

SC_RULES: dict[str, Rule] = {r.id: r for r in _SC_RULES}

# (algo, jit, rule) -> justification. A finding matching a key here is
# reported as suppressed, not failing; the justification is MANDATORY and
# printed in verbose output so every suppression stays auditable (same
# contract as sheeplint's `# sheeplint: disable=... — why`).
SUPPRESSIONS: dict[tuple[str, str, str], str] = {}

_HOST_PRIMS = {
    "pure_callback",
    "io_callback",
    "debug_callback",
    "callback",
    "infeed",
    "outfeed",
}

_FLOAT_WIDTH = {"bfloat16": 16, "float16": 16, "float32": 32, "float64": 64}


@dataclasses.dataclass
class Finding:
    rule: Rule
    algo: str
    jit: str
    message: str
    suppressed: str | None = None  # justification when suppressed

    def format(self) -> str:
        sup = f" [suppressed: {self.suppressed}]" if self.suppressed else ""
        return (
            f"{self.algo}/{self.jit}: {self.rule.id} [{self.rule.severity}] "
            f"{self.message}{sup}"
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule.id,
            "severity": self.rule.severity,
            "algo": self.algo,
            "jit": self.jit,
            "message": self.message,
            "suppressed": self.suppressed,
        }


@dataclasses.dataclass
class JitReport:
    algo: str
    name: str
    fingerprint: dict | None = None
    findings: list[Finding] = dataclasses.field(default_factory=list)
    error: str | None = None  # not analyzable (no example / not lowerable)

    @property
    def failing(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _subjaxprs(params: dict) -> Iterator[Any]:
    """Yield every (Closed)Jaxpr reachable from an eqn's params — covers
    pjit/scan/remat ('jaxpr'), while ('cond_jaxpr'/'body_jaxpr'), cond
    ('branches'), custom_* ('call_jaxpr'), and any future param shape that
    stores jaxprs in lists/tuples."""
    import jax

    def walk(v):
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (list, tuple)):
            for el in v:
                yield from walk(el)

    for v in params.values():
        yield from walk(v)


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every eqn of `jaxpr` (a core.Jaxpr or ClosedJaxpr), recursively
    through call/control-flow sub-jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for sub in _subjaxprs(eqn.params):
            yield from iter_eqns(sub)


def _aval_str(aval: Any) -> str:
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    if dtype is None:
        return str(aval)
    s = f"{dtype.name}[{','.join(str(d) for d in (shape or ()))}]"
    if getattr(aval, "weak_type", False):
        s += "~"  # weak-typed leaf
    return s


def _all_avals(closed: Any) -> Iterator[Any]:
    inner = closed.jaxpr
    for v in (*inner.invars, *inner.outvars):
        if hasattr(v, "aval"):
            yield v.aval
    for eqn in iter_eqns(inner):
        for v in (*eqn.invars, *eqn.outvars):
            if hasattr(v, "aval"):
                yield v.aval


# ---------------------------------------------------------------------------
# analyzers (one per SC rule, all pure functions of the IR)
# ---------------------------------------------------------------------------


def _check_sc001(closed: Any, audit_bf16: bool) -> Iterator[str]:
    f64 = sorted(
        {
            _aval_str(a)
            for a in _all_avals(closed)
            if getattr(getattr(a, "dtype", None), "name", "") == "float64"
        }
    )
    if f64:
        yield (
            f"float64 values in the traced program ({len(f64)} distinct "
            f"avals, e.g. {f64[0]}) — x64 leaked into a TPU-targeted jit"
        )
    for eqn in iter_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = getattr(eqn.invars[0].aval.dtype, "name", "")
        dst = getattr(eqn.outvars[0].aval.dtype, "name", "")
        if src not in _FLOAT_WIDTH or dst not in _FLOAT_WIDTH:
            continue
        if _FLOAT_WIDTH[dst] <= _FLOAT_WIDTH[src]:
            continue
        if dst == "float64":
            yield f"widening convert {src}->{dst} ({_aval_str(eqn.outvars[0].aval)})"
        elif audit_bf16 and src == "bfloat16":
            yield (
                f"bf16 upcast: convert {src}->{dst} "
                f"({_aval_str(eqn.outvars[0].aval)}) — audit whether this "
                "upcast is an intended fp32 island (moments/reductions)"
            )


def _check_sc002(closed: Any) -> Iterator[str]:
    hits: dict[str, int] = {}
    for eqn in iter_eqns(closed):
        if eqn.primitive.name in _HOST_PRIMS:
            hits[eqn.primitive.name] = hits.get(eqn.primitive.name, 0) + 1
    for name, count in sorted(hits.items()):
        yield f"{count}x `{name}` traced into the jit"


def _donated_flags(lowered: Any, closed: Any) -> list[bool]:
    """Donation flags aligned with the closed jaxpr's invars (flat arg
    order). Falls back to all-False when args_info is unavailable or the
    flattening disagrees with the jaxpr arity."""
    import jax

    try:
        leaves = jax.tree_util.tree_leaves(lowered.args_info)
        flags = [bool(getattr(info, "donated", False)) for info in leaves]
    except Exception:
        return [False] * len(closed.jaxpr.invars)
    if len(flags) != len(closed.jaxpr.invars):
        return [False] * len(closed.jaxpr.invars)
    return flags


def _check_sc003(closed: Any, donated: list[bool]) -> Iterator[str]:
    inner = closed.jaxpr
    if not any(donated):
        return
    used: set[int] = set()
    for eqn in iter_eqns(inner):
        for v in eqn.invars:
            if hasattr(v, "aval"):
                used.add(id(v))
    out_ids = [id(v) for v in inner.outvars if hasattr(v, "aval")]
    # greedy aval matching: every output reuses at most one donated buffer
    free_outputs: list[tuple[Any, Any]] = [
        (getattr(v.aval, "shape", None), getattr(v.aval, "dtype", None))
        for v in inner.outvars
        if hasattr(v, "aval")
    ]
    for i, (var, is_donated) in enumerate(zip(inner.invars, donated)):
        if not is_donated:
            continue
        alias_count = out_ids.count(id(var))
        if alias_count >= 2:
            yield (
                f"donated arg {i} ({_aval_str(var.aval)}) is returned as "
                f"{alias_count} outputs — one buffer cannot alias into both"
            )
            continue
        if id(var) not in used and alias_count == 0:
            yield (
                f"donated arg {i} ({_aval_str(var.aval)}) is dead: never "
                "read and never returned — the caller's buffer is "
                "invalidated for nothing"
            )
            continue
        key = (getattr(var.aval, "shape", None), getattr(var.aval, "dtype", None))
        if key in free_outputs:
            free_outputs.remove(key)  # claimed by this donation
        else:
            yield (
                f"donated arg {i} ({_aval_str(var.aval)}) has no "
                "shape/dtype-matching output left to reuse its buffer — "
                "XLA drops the alias silently"
            )


def _check_sc004(closed: Any) -> Iterator[str]:
    # top-level weak inputs: the registered example (and therefore the live
    # call site it mirrors) feeds a python scalar straight into the jit —
    # this is how sheepcheck caught ppo_decoupled's gae still taking raw
    # `args.gamma`/`args.gae_lambda` after PR 2 fixed coupled ppo
    for i, var in enumerate(closed.jaxpr.invars):
        aval = getattr(var, "aval", None)
        if aval is not None and getattr(aval, "weak_type", False):
            yield (
                f"jit input {i} is weak-typed ({_aval_str(aval)}) — the "
                "call site passes a python scalar; wrap it once as "
                "jnp.float32(...)"
            )
    for eqn in iter_eqns(closed):
        if eqn.primitive.name != "scan":
            continue
        body = eqn.params.get("jaxpr")
        if body is None:
            continue
        inner = getattr(body, "jaxpr", body)
        nc = int(eqn.params.get("num_consts", 0))
        nk = int(eqn.params.get("num_carry", 0))
        carry_in = inner.invars[nc : nc + nk]
        carry_out = inner.outvars[:nk]
        for i, vin in enumerate(carry_in):
            a_in = getattr(vin, "aval", None)
            a_out = getattr(carry_out[i], "aval", None) if i < len(carry_out) else None
            if a_in is not None and getattr(a_in, "weak_type", False):
                yield (
                    f"scan carry {i} is weak-typed ({_aval_str(a_in)}) — "
                    "initialize it with a concrete dtype"
                )
            elif (
                a_in is not None
                and a_out is not None
                and (
                    getattr(a_in, "dtype", None) != getattr(a_out, "dtype", None)
                    or getattr(a_in, "shape", None) != getattr(a_out, "shape", None)
                )
            ):
                yield (
                    f"scan carry {i} is unstable: in {_aval_str(a_in)} vs "
                    f"out {_aval_str(a_out)}"
                )


def _check_sc005(closed: Any) -> Iterator[str]:
    from ..compile.partition import compile_budget_s, predicted_cpu_compile_seconds

    convs = [e for e in iter_eqns(closed) if e.primitive.name == "conv_general_dilated"]
    if not convs:
        return
    batch = 1
    grad_convs = 0
    for eqn in convs:
        lhs_dil = eqn.params.get("lhs_dilation") or ()
        if any(d > 1 for d in lhs_dil):
            grad_convs += 1
        dn = eqn.params.get("dimension_numbers")
        lhs_shape = getattr(eqn.invars[0].aval, "shape", ())
        bdim = dn.lhs_spec[0] if dn is not None else 0
        if lhs_shape:
            batch = max(batch, int(lhs_shape[bdim]))
    predicted = predicted_cpu_compile_seconds(len(convs), batch)
    budget = compile_budget_s()
    if (grad_convs and predicted > budget) or predicted > 10 * budget:
        yield (
            f"{len(convs)} convolutions ({grad_convs} gradient-class, "
            f"lhs-dilated) at batch {batch}: predictor says "
            f"{predicted:.0f}s on XLA:CPU (budget {budget:.0f}s) — the "
            "regime --split_update auto / --recon_chunk partition"
        )


def analyze_closed_jaxpr(
    closed: Any,
    *,
    algo: str = "<fixture>",
    name: str = "<jit>",
    donated: list[bool] | None = None,
    rules: set[str] | None = None,
    audit_bf16: bool = False,
) -> list[Finding]:
    """Run the SC analyzers over one ClosedJaxpr. `donated` is the per-flat-
    invar donation mask (from `Lowered.args_info`); fixture tests can pass
    it directly."""
    if donated is None:
        donated = [False] * len(closed.jaxpr.invars)
    checks: list[tuple[str, Iterable[str]]] = [
        ("SC001", _check_sc001(closed, audit_bf16)),
        ("SC002", _check_sc002(closed)),
        ("SC003", _check_sc003(closed, donated)),
        ("SC004", _check_sc004(closed)),
        ("SC005", _check_sc005(closed)),
    ]
    out: list[Finding] = []
    for rule_id, messages in checks:
        if rules is not None and rule_id not in rules:
            continue
        for message in messages:
            finding = Finding(SC_RULES[rule_id], algo, name, message)
            finding.suppressed = SUPPRESSIONS.get((algo, name, rule_id))
            out.append(finding)
    return out


# ---------------------------------------------------------------------------
# fingerprints + budget ledger
# ---------------------------------------------------------------------------


def _count_bf16_upcasts(closed: Any) -> int:
    """Number of bf16->f32 `convert_element_type` eqns in the program —
    the per-jit mixed-precision fingerprint. For an f32-only jit this is
    0; for a declared-bf16 jit it is exactly the committed fp32-island
    count the audit gate (`--gate-bf16` / check_budget) enforces."""
    count = 0
    for eqn in iter_eqns(closed):
        if eqn.primitive.name != "convert_element_type":
            continue
        src = getattr(eqn.invars[0].aval.dtype, "name", "")
        dst = getattr(eqn.outvars[0].aval.dtype, "name", "")
        if src == "bfloat16" and dst == "float32":
            count += 1
    return count


def _count_int8_ops(closed: Any) -> int:
    """Number of eqns touching an int8 aval (invars or outvars) — the
    per-jit quantization fingerprint. For an unquantized jit this is 0;
    for a `--quant int8` serving rung it counts the quantize / int8
    dot_general / dequantize chain, and the budget gate treats a SHRINK
    as lost quantization coverage (a rung silently serving full-width
    again) the same way the bf16 gate treats lost bfloat16."""
    count = 0
    for eqn in iter_eqns(closed):
        for v in (*eqn.invars, *eqn.outvars):
            aval = getattr(v, "aval", None)
            if getattr(getattr(aval, "dtype", None), "name", "") == "int8":
                count += 1
                break
    return count


def fingerprint_jaxpr(closed: Any, lowered: Any = None) -> dict:
    """The compile-cost fingerprint of one jit: what the budget ledger
    commits and the CI drift gate compares."""
    prims: dict[str, int] = {}
    op_count = 0
    for eqn in iter_eqns(closed):
        op_count += 1
        prims[eqn.primitive.name] = prims.get(eqn.primitive.name, 0) + 1
    dtypes = sorted(
        {
            getattr(getattr(a, "dtype", None), "name", "")
            for a in _all_avals(closed)
        }
        - {""}
    )
    fp: dict[str, Any] = {
        "in_avals": [_aval_str(v.aval) for v in closed.jaxpr.invars],
        "out_avals": [_aval_str(v.aval) for v in closed.jaxpr.outvars],
        "op_count": op_count,
        "primitives": dict(sorted(prims.items())),
        "dtypes": dtypes,
        # the DECLARED fp32 islands of a mixed-precision jit: every
        # committed bf16->f32 convert is an intended loss/logit/moment
        # boundary; the gate fails when a derived program exceeds this
        # count (a new SILENT upcast) — see check_budget
        "bf16_upcasts": _count_bf16_upcasts(closed),
        # the committed quantization coverage of an int8 serving rung:
        # check_budget fails a declared-int8 jit whose count shrinks (a
        # dequantized layer serving full-width under the int8 flag)
        "int8_ops": _count_int8_ops(closed),
        "donated": 0,
        "flops": None,
        "bytes_accessed": None,
    }
    if lowered is not None:
        donated = _donated_flags(lowered, closed)
        fp["donated"] = int(sum(donated))
        try:
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            if cost:
                flops = cost.get("flops")
                touched = cost.get("bytes accessed")
                fp["flops"] = None if flops is None else round(float(flops), 1)
                fp["bytes_accessed"] = (
                    None if touched is None else round(float(touched), 1)
                )
        # sheeplint: disable=SL012 — cost model missing on this backend is an
        # expected configuration, not a failure; the fingerprint stays valid
        except Exception:
            pass  # cost model unavailable on this backend: fingerprint without it
    return fp


def analyze_entry(
    algo: str,
    entry: Any,
    rules: set[str] | None = None,
    audit_bf16: bool = False,
) -> JitReport:
    """Abstract-eval one CompilePlan entry (fn + example thunk) and analyze
    it. No execution: `trace` + `lower` only."""
    from ..compile.plan import avals_of

    report = JitReport(algo=algo, name=entry.name)
    fn, example = entry.fn, entry.example
    if example is None:
        report.error = "no example thunk (registered for timing only)"
        return report
    if not hasattr(fn, "trace") or not hasattr(fn, "lower"):
        report.error = "not traceable (wrapped callable without .trace/.lower)"
        return report
    try:
        specs = avals_of(example())
        traced = fn.trace(*specs)
        closed = traced.jaxpr
        lowered = traced.lower()
    except Exception as err:
        report.error = f"trace failed: {type(err).__name__}: {err}"[:300]
        return report
    report.fingerprint = fingerprint_jaxpr(closed, lowered)
    report.findings = analyze_closed_jaxpr(
        closed,
        algo=algo,
        name=entry.name,
        donated=_donated_flags(lowered, closed),
        rules=rules,
        audit_bf16=audit_bf16,
    )
    return report


def build_budget(reports: list[JitReport], op_count_frac: float = 0.25) -> dict:
    """The committed ledger: per-jit fingerprints + the drift tolerances
    they are gated with."""
    import jax

    return {
        "version": 1,
        "jax_version": jax.__version__,
        "tolerance": {"op_count_frac": op_count_frac},
        "jits": {
            f"{r.algo}/{r.name}": r.fingerprint
            for r in reports
            if r.fingerprint is not None
        },
    }


def check_budget(ledger: dict, derived: dict) -> tuple[list[str], list[str]]:
    """Compare a freshly derived budget against the committed ledger.

    Returns `(failures, notes)`. Failures are the ISSUE-gated drift classes
    — added/removed jits, new dtypes, op-count growth past tolerance, lost
    donations. Improvements (shrinking op counts, new donations) and
    primitive-mix changes are notes: visible, not blocking, and a prompt to
    refresh the ledger with `--update-budget`."""
    failures: list[str] = []
    notes: list[str] = []
    tol = float(ledger.get("tolerance", {}).get("op_count_frac", 0.25))
    old, new = ledger.get("jits", {}), derived.get("jits", {})
    for key in sorted(set(old) - set(new)):
        failures.append(f"{key}: jit disappeared from the plan (ledger has it)")
    for key in sorted(set(new) - set(old)):
        failures.append(f"{key}: new jit not in the ledger")
    for key in sorted(set(old) & set(new)):
        o, n = old[key], new[key]
        new_dtypes = sorted(set(n.get("dtypes", [])) - set(o.get("dtypes", [])))
        if new_dtypes:
            failures.append(f"{key}: new dtypes {new_dtypes}")
        # mixed-precision drift (ISSUE 9): a jit whose ledger entry declares
        # bf16 compute must keep it — losing bfloat16 from the dtype set is
        # a silent full-width regression, and growing the bf16->f32 convert
        # count beyond the committed fp32 islands is a silent upcast
        if "bfloat16" in o.get("dtypes", []):
            if "bfloat16" not in n.get("dtypes", []):
                failures.append(
                    f"{key}: declared-bf16 jit lost its bfloat16 compute "
                    "(silently upcast to full width)"
                )
            ou = o.get("bf16_upcasts")
            nu = n.get("bf16_upcasts")
            if ou is not None and nu is not None:
                if int(nu) > int(ou):
                    failures.append(
                        f"{key}: bf16->f32 upcasts grew {ou} -> {nu} — "
                        "undeclared fp32 island(s) inside a declared-bf16 "
                        "jit (audit with tools/sheepcheck.py --audit-bf16, "
                        "then --update-budget if intended)"
                    )
                elif int(nu) < int(ou):
                    notes.append(
                        f"{key}: bf16 upcasts shrank {ou} -> {nu} — refresh "
                        "the ledger"
                    )
        # quantization drift (ISSUE 20): a jit whose ledger entry declares
        # int8 compute (the `@int8` serving twins) must keep it — losing
        # int8 from the dtype set, or shrinking the int8-op count, means a
        # quantized rung silently serves full-width math again under the
        # int8 flag. Growth is a note: MORE quantized coverage is an
        # improvement that wants a ledger refresh, not a block.
        if "int8" in o.get("dtypes", []):
            if "int8" not in n.get("dtypes", []):
                failures.append(
                    f"{key}: declared-int8 jit lost its int8 compute "
                    "(quantized rung silently dequantized to full width)"
                )
            oi = o.get("int8_ops")
            ni = n.get("int8_ops")
            if oi is not None and ni is not None:
                if int(ni) < int(oi):
                    failures.append(
                        f"{key}: int8 ops shrank {oi} -> {ni} — lost "
                        "quantization coverage inside a declared-int8 jit "
                        "(re-run the capture, then --update-budget if "
                        "intended)"
                    )
                elif int(ni) > int(oi):
                    notes.append(
                        f"{key}: int8 ops grew {oi} -> {ni} — refresh the "
                        "ledger"
                    )
        oc, nc = int(o.get("op_count", 0)), int(n.get("op_count", 0))
        if nc > oc * (1.0 + tol):
            failures.append(
                f"{key}: op count grew {oc} -> {nc} "
                f"(+{(nc - oc) / max(oc, 1):.0%}, tolerance {tol:.0%})"
            )
        elif nc < oc * (1.0 - tol):
            notes.append(
                f"{key}: op count shrank {oc} -> {nc} — refresh the ledger"
            )
        od, nd = int(o.get("donated", 0)), int(n.get("donated", 0))
        if nd < od:
            failures.append(f"{key}: lost donations ({od} -> {nd})")
        elif nd > od:
            notes.append(f"{key}: gained donations ({od} -> {nd})")
        if o.get("primitives") != n.get("primitives") and not (
            new_dtypes or nc > oc * (1.0 + tol)
        ):
            changed = {
                p
                for p in set(o.get("primitives", {})) ^ set(n.get("primitives", {}))
            }
            if changed:
                notes.append(
                    f"{key}: primitive mix changed ({sorted(changed)[:6]})"
                )
    return failures, notes


# ---------------------------------------------------------------------------
# capture driver: instantiate a main's CompilePlan without running it
# ---------------------------------------------------------------------------

_DREAMER_TINY = [
    "--env_id", "discrete_dummy",
    "--num_envs", "1",
    "--sync_env",
    "--dry_run",
    "--per_rank_batch_size", "2",
    "--per_rank_sequence_length", "8",
    "--buffer_size", "64",
    "--learning_starts", "0",
    "--train_every", "1",
    "--horizon", "4",
    "--dense_units", "8",
    "--cnn_channels_multiplier", "2",
    "--recurrent_state_size", "8",
    "--hidden_size", "8",
    "--stochastic_size", "4",
    "--mlp_layers", "1",
    "--cnn_keys", "rgb",
]

_SAC_TINY = [
    "--env_id", "Pendulum-v1",
    "--num_envs", "1",
    "--sync_env",
    "--dry_run",
    "--per_rank_batch_size", "4",
    "--buffer_size", "16",
    "--learning_starts", "0",
    "--gradient_steps", "1",
    "--actor_hidden_size", "16",
    "--critic_hidden_size", "16",
]

# The shape-capture argv per algo main: tiny widths, dummy/classic-control
# envs, single data device (decoupled topologies need 2: player + trainer
# sub-meshes). These define the avals the committed budget.json fingerprints
# are derived at — change them and the ledger must be refreshed.
CAPTURE_ARGV: dict[str, list[str]] = {
    "ppo": [
        "--env_id", "discrete_dummy",
        "--num_envs", "1",
        "--sync_env",
        "--dry_run",
        "--num_devices", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
        "--cnn_channels_multiplier", "1",
        "--cnn_features_dim", "16",
        "--mlp_features_dim", "16",
    ],
    "ppo_decoupled": [
        "--env_id", "CartPole-v1",
        "--num_envs", "1",
        "--sync_env",
        "--dry_run",
        "--num_devices", "2",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--update_epochs", "1",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--actor_hidden_size", "8",
        "--critic_hidden_size", "8",
    ],
    "ppo_recurrent": [
        "--env_id", "CartPole-v1",
        "--num_envs", "2",
        "--sync_env",
        "--dry_run",
        "--num_devices", "1",
        "--rollout_steps", "8",
        "--per_rank_batch_size", "4",
        "--per_rank_num_batches", "2",
        "--update_epochs", "2",
        "--dense_units", "8",
        "--mlp_layers", "1",
    ],
    "sac": ["--num_devices", "1", *_SAC_TINY],
    "sac_decoupled": ["--num_devices", "2", *_SAC_TINY],
    "droq": ["--num_devices", "1", *_SAC_TINY],
    "sac_ae": [
        "--env_id", "continuous_dummy",
        "--num_envs", "1",
        "--sync_env",
        "--dry_run",
        "--num_devices", "1",
        "--per_rank_batch_size", "2",
        "--buffer_size", "8",
        "--learning_starts", "0",
        "--gradient_steps", "1",
        "--actor_hidden_size", "16",
        "--critic_hidden_size", "16",
        "--features_dim", "16",
        "--dense_units", "8",
        "--mlp_layers", "1",
        "--cnn_channels_multiplier", "1",
    ],
    "dreamer_v1": ["--num_devices", "1", *_DREAMER_TINY],
    "dreamer_v2": ["--num_devices", "1", *_DREAMER_TINY, "--discrete_size", "4"],
    "dreamer_v3": ["--num_devices", "1", *_DREAMER_TINY, "--discrete_size", "4"],
    "dreamer_v3_decoupled": [
        "--num_devices", "2", *_DREAMER_TINY, "--discrete_size", "4",
    ],
    "p2e_dv1": ["--num_devices", "1", *_DREAMER_TINY],
    "p2e_dv2": ["--num_devices", "1", *_DREAMER_TINY, "--discrete_size", "4"],
    # serving tier (ISSUE 15): one fixed-shape policy jit per batch-ladder
    # rung (`serve/policy_b{1,2,4}`); the checkpoint-free --model_argv init
    # builds the same tiny SAC the `sac` spec captures. The ledger's
    # argument/peak bytes per rung are what `serve/ladder.py` scales to
    # size production ladders without trial compiles.
    "serve": [
        "--algo", "sac",
        "--max_batch", "4",
        "--model_argv",
        "--env_id Pendulum-v1 --actor_hidden_size 16 --critic_hidden_size 16",
    ],
}

# Named capture VARIANTS: flag combinations of the same mains that register
# ADDITIONAL jits the default argv never builds — the PR-6 Anakin path
# (`--env_backend jax`), whose fully-jitted rollout collector is exactly
# the kind of program sheepcheck exists for, and since ISSUE 9 one
# `<algo>@bf16` variant PER MAIN (`--precision bfloat16`): the same jits
# traced under the mixed-precision policy, whose committed fingerprints
# (dtype set incl. bfloat16 + the `bf16_upcasts` fp32-island count) are
# what the bf16 half of check_budget and `--gate-bf16` enforce. Variant
# argv is APPENDED to the base algo's CAPTURE_ARGV (later flags win), and
# reports/ledger keys use the variant name (`ppo@anakin/anakin_rollout`).
_BF16 = ["--precision", "bfloat16"]

CAPTURE_VARIANTS: dict[str, tuple[str, list[str]]] = {
    "ppo@anakin": ("ppo", ["--env_backend", "jax", "--env_id", "CartPole-v1"]),
    "dreamer_v3@anakin": (
        "dreamer_v3",
        ["--env_backend", "jax", "--env_id", "pixeltoy"],
    ),
    # the DV3 player ladder: recurrent PlayerState in, mode actions out —
    # same serve main, dreamer_v3 policy family at _DREAMER_TINY widths
    "dreamer_v3@serve": (
        "serve",
        [
            "--algo", "dreamer_v3",
            "--model_argv",
            "--env_id discrete_dummy --cnn_keys rgb --dense_units 8 "
            "--cnn_channels_multiplier 2 --recurrent_state_size 8 "
            "--hidden_size 8 --stochastic_size 4 --discrete_size 4 "
            "--mlp_layers 1",
        ],
    ),
    # serve takes precision through the nested --model_argv (ServeArgs has
    # no --precision of its own): the whole string re-specifies last-wins,
    # and policies.py threads targs.precision into both policy builds
    "serve@bf16": (
        "serve",
        [
            "--model_argv",
            "--env_id Pendulum-v1 --actor_hidden_size 16 "
            "--critic_hidden_size 16 --precision bfloat16",
        ],
    ),
    **{f"{algo}@bf16": (algo, list(_BF16)) for algo in (
        "ppo",
        "ppo_decoupled",
        "ppo_recurrent",
        "sac",
        "sac_decoupled",
        "droq",
        "sac_ae",
        "dreamer_v1",
        "dreamer_v2",
        "dreamer_v3",
        "dreamer_v3_decoupled",
        "p2e_dv1",
        "p2e_dv2",
    )},
    # the ISSUE 20 quantized twins: same serve mains under `--quant int8`
    # (capture mode quantizes the checkpoint-free init and registers the
    # int8 step for every rung — no timed acceptance), so the committed
    # fingerprints carry the int8 dtype + `int8_ops` coverage count the
    # int8 half of check_budget enforces, and sheepmem can pair each
    # rung's argument bytes against its full-width twin
    "serve@int8": ("serve", ["--quant", "int8"]),
}
# dreamer_v3@serve@int8 composes the DV3 player-ladder variant's argv with
# the quant flag (the dict literal can't self-reference its own entries)
CAPTURE_VARIANTS["dreamer_v3@serve@int8"] = (
    CAPTURE_VARIANTS["dreamer_v3@serve"][0],
    [*CAPTURE_VARIANTS["dreamer_v3@serve"][1], "--quant", "int8"],
)


def declares_bf16(fingerprint: dict) -> bool:
    """True when a ledger entry declares bf16 compute (the `--gate-bf16`
    population: its upcast count is enforced, f32-only jits stay
    audit-only)."""
    return "bfloat16" in (fingerprint or {}).get("dtypes", [])


def declares_int8(fingerprint: dict) -> bool:
    """True when a ledger entry declares int8 compute (the `@int8` serving
    twins: check_budget enforces their dtype set and int8-op count, and
    sheepmem pairs their argument bytes against the full-width twin)."""
    return "int8" in (fingerprint or {}).get("dtypes", [])


def resolve_capture(spec: str) -> tuple[str, list[str]]:
    """Map a capture spec (an algo name or a CAPTURE_VARIANTS key) to the
    `(algo, extra_argv)` pair `capture_plan` consumes."""
    if spec in CAPTURE_VARIANTS:
        return CAPTURE_VARIANTS[spec]
    return spec, []


def _compose_base(base: list[str], extra: list[str]) -> list[str]:
    """`later flags win` for BOOL pairs too: argparse makes `--x`/`--no_x`
    mutually exclusive within one argv, so a variant that flips a base
    bool (e.g. the @remat twins' `--no_dry_run` over _DREAMER_TINY's
    `--dry_run`) must DROP the base token rather than append its negation
    after it. Only standalone flags (no following value token) are
    dropped — value-bearing flags already compose by last-wins."""
    negations = {f"--{t[5:]}" for t in extra if t.startswith("--no_")}
    negations |= {
        f"--no_{t[2:]}"
        for t in extra
        if t.startswith("--") and not t.startswith("--no_")
    }
    out: list[str] = []
    i = 0
    while i < len(base):
        tok = base[i]
        standalone = not (
            i + 1 < len(base) and not str(base[i + 1]).startswith("--")
        )
        if tok.startswith("--") and tok in negations and standalone:
            i += 1
            continue
        out.append(tok)
        i += 1
    return out


def capture_plan(algo: str, root_dir: str, extra_argv: list[str] | None = None):
    """Run `algo`'s main in capture mode and return its CompilePlan.

    Sets `SHEEPRL_TPU_PLAN_MODE=capture` (CompilePlan.start() raises
    CaptureComplete before the first collection step) and
    `SHEEPRL_TPU_DONATE=1` (donation metadata must survive into the
    lowering for SC003/the donation fingerprint — nothing executes, so the
    CPU persistent-cache donation hazard is moot)."""
    import sheeprl_tpu.algos  # noqa: F401 — fire @register_algorithm decorators
    from sheeprl_tpu.utils.registry import tasks

    from ..compile.plan import CaptureComplete

    if algo not in tasks:
        raise KeyError(f"unknown algo {algo!r}; registered: {sorted(tasks)}")
    argv = [
        *_compose_base(CAPTURE_ARGV.get(algo, []), extra_argv or []),
        "--platform", "cpu",
        "--root_dir", root_dir,
        "--run_name", f"sheepcheck_{algo}",
        *(extra_argv or []),
    ]
    saved = {
        k: os.environ.get(k) for k in ("SHEEPRL_TPU_PLAN_MODE", "SHEEPRL_TPU_DONATE")
    }
    os.environ["SHEEPRL_TPU_PLAN_MODE"] = "capture"
    os.environ["SHEEPRL_TPU_DONATE"] = "1"
    try:
        tasks[algo](argv)
    except CaptureComplete as done:
        return done.plan
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    raise RuntimeError(
        f"{algo}: main returned without calling plan.start() — no plan captured"
    )


def analyze_plan(
    algo: str,
    plan: Any,
    rules: set[str] | None = None,
    audit_bf16: bool = False,
) -> list[JitReport]:
    return [
        analyze_entry(algo, entry, rules=rules, audit_bf16=audit_bf16)
        for entry in plan._entries
    ]


# ---------------------------------------------------------------------------
# ledger persistence: per-algo dir layout (+ legacy single-blob reading)
# ---------------------------------------------------------------------------
#
# The ledger lives in `analysis/budget/` as one file per algo/variant spec
# (`ppo.json`, `ppo@anakin.json`, ...) plus `_meta.json` (version,
# jax_version, tolerances) — deterministic key order, one jit per block, so
# a PR's ledger diff reads as "which jits of which algo changed". Each spec
# file can hold several SECTIONS: `jits` (sheepcheck's compile-cost
# fingerprints), `comms` and `edges` (sheepshard's collective/contract
# fingerprints), and `memory` (sheepmem's buffer-lifetime fingerprints);
# savers only rewrite their own sections. The pre-split single-blob
# `analysis/budget.json` is NO LONGER readable (the PR-8 "one release"
# grace period is over): a blob path without the dir layout raises with a
# pointer at the migration, instead of silently gating against stale data.

_LEDGER_SECTIONS = ("jits", "comms", "edges", "memory")


def budget_dir_of(path: str) -> str:
    """Map a ledger path to its dir-layout root: `analysis/budget.json` ->
    `analysis/budget`; a dir path passes through."""
    if os.path.isdir(path):
        return path
    root, ext = os.path.splitext(path)
    return root if ext == ".json" else path


def budget_exists(path: str) -> bool:
    return os.path.isdir(budget_dir_of(path)) or os.path.exists(path)


def load_budget(path: str) -> dict:
    """Read the ledger in the per-algo dir layout. Empty sections are
    dropped so a jits-only ledger round-trips exactly. A legacy pre-split
    single-blob `budget.json` (without the dir next to it) is an ERROR —
    rebuild the dir layout rather than gating against stale data."""
    d = budget_dir_of(path)
    if not os.path.isdir(d):
        if os.path.exists(path):
            raise RuntimeError(
                f"{path} is a legacy single-blob budget ledger; the blob "
                "reader was removed (ISSUE 11). The ledger lives in the "
                f"per-algo dir layout now ({d}/_meta.json + one "
                "<spec>.json per algo/variant) — re-run "
                "`tools/sheepcheck.py --update-budget`, "
                "`tools/sheepshard.py --update-budget` and "
                "`tools/sheepmem.py --update-budget` to rebuild it, then "
                "delete the blob."
            )
        raise FileNotFoundError(f"no budget ledger dir at {d}")
    out: dict = {section: {} for section in _LEDGER_SECTIONS}
    meta_path = os.path.join(d, "_meta.json")
    if os.path.exists(meta_path):
        with open(meta_path, encoding="utf-8") as fh:
            out.update(json.load(fh))
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json") or name == "_meta.json":
            continue
        with open(os.path.join(d, name), encoding="utf-8") as fh:
            blob = json.load(fh)
        for section in _LEDGER_SECTIONS:
            out[section].update(blob.get(section, {}))
    for section in _LEDGER_SECTIONS:
        if not out.get(section):
            out.pop(section, None)
    return out


def save_budget(
    budget: dict, path: str, sections: tuple[str, ...] = ("jits",)
) -> None:
    """Write `budget` in the per-algo dir layout. Only `sections` are
    rewritten — and they are rewritten COMPLETELY: a spec file whose
    entries vanished from `budget` has that section stripped (callers
    doing partial sweeps merge into the loaded ledger first). Other
    sections in the files, and a legacy blob at `path`, are left alone."""
    d = budget_dir_of(path)
    os.makedirs(d, exist_ok=True)
    meta_path = os.path.join(d, "_meta.json")
    meta: dict = {}
    if os.path.exists(meta_path):
        with open(meta_path, encoding="utf-8") as fh:
            meta = json.load(fh)
    tol = dict(meta.get("tolerance", {}))
    tol.update(budget.get("tolerance", {}))
    meta.update({k: budget[k] for k in ("version", "jax_version") if k in budget})
    if tol:
        meta["tolerance"] = tol
    _write_json(meta, meta_path)
    by_spec: dict[str, dict[str, dict]] = {}
    for section in sections:
        for key, val in budget.get(section, {}).items():
            spec = key.split("/", 1)[0]
            by_spec.setdefault(spec, {}).setdefault(section, {})[key] = val
    existing = {
        name[: -len(".json")]
        for name in os.listdir(d)
        if name.endswith(".json") and name != "_meta.json"
    }
    for spec in sorted(existing | set(by_spec)):
        spec_path = os.path.join(d, f"{spec}.json")
        blob: dict = {}
        if os.path.exists(spec_path):
            with open(spec_path, encoding="utf-8") as fh:
                blob = json.load(fh)
        changed = not os.path.exists(spec_path)
        for section in sections:
            had = blob.pop(section, None)
            new_sec = by_spec.get(spec, {}).get(section)
            if new_sec:
                blob[section] = new_sec
            changed = changed or new_sec != had
        if not changed:
            # untouched managed sections: leave the file byte-identical —
            # a spec file carrying only a foreign section (e.g. sheepsync's
            # `concurrency`) must survive a jits/memory sweep unrewritten
            continue
        if any(blob.get(section) for section in blob):
            _write_json(blob, spec_path)
        elif os.path.exists(spec_path):
            os.remove(spec_path)


def _write_json(obj: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, indent=1, sort_keys=True)
        fh.write("\n")
