"""AST engine for sheeplint (see rules.py for the catalog).

The engine is deliberately *syntactic*: it never imports the linted module,
so it is safe on files with heavy import sides (algo mains spin up envs at
import of their deps) and runs in milliseconds over the whole repo. The
price is heuristic scoping — "inside a jit body" means one of:

  - a function decorated with `jax.jit` / `donating_jit` /
    `@partial(jax.jit, ...)` / `jax.vmap` / `jax.pmap`;
  - a function or lambda *passed* to one of those transforms, or used as a
    `lax.scan` / `lax.cond` / `lax.while_loop` / `lax.fori_loop` /
    `lax.switch` / `checkify.checkify` body;
  - any def nested inside one of the above (closures are traced inline).

Cross-module dataflow (a helper jitted in another file) is out of scope;
the rules are tuned so that what they do catch is near-certainly real, and
anything intentional is one `# sheeplint: disable=<rule>` comment away.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from pathlib import Path
from typing import Iterable, Optional

from .rules import RULES, Violation

__all__ = ["lint_source", "lint_file", "lint_paths", "iter_python_files"]

_SUPPRESS_RE = re.compile(
    r"sheeplint:\s*(disable(?:-file)?)\s*=\s*([A-Za-z0-9_,\s]+?)\s*(?:[-—(].*)?$"
)

# transforms whose FIRST positional argument is traced when called
_WRAP_TRANSFORMS = {"jit", "vmap", "pmap", "donating_jit", "named_call"}
# transforms tracing callables at given positional indexes
_BODY_ARG_TRANSFORMS = {
    "scan": (0,),
    "associative_scan": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "checkify": (0,),
    "custom_jvp": (0,),
    "grad": (0,),
    "value_and_grad": (0,),
}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_SHAPE_ATTRS = {"shape", "ndim", "size", "dtype", "itemsize"}
# callback entry points that trace a host round-trip into the program
_CALLBACK_LEAVES = {
    "print",  # jax.debug.print (scoped below to jax.debug/debug roots)
    "callback",  # jax.debug.callback
    "io_callback",
    "pure_callback",
    "id_tap",  # legacy host_callback
    "call",  # host_callback.call (scoped to host_callback root)
}


def _parse_suppressions(src: str) -> tuple[dict[int, set[str]], set[str]]:
    """Map line -> suppressed rule ids, plus file-level suppressions.

    A trailing `# sheeplint: disable=SL001,SL002` suppresses its own line; a
    comment alone on a line also suppresses the next line (so directives can
    sit above decorators or long calls). `disable-file=` applies everywhere.
    Free-text justifications after the id list (dash/paren separated) are
    encouraged and ignored by the parser.
    """
    per_line: dict[int, set[str]] = {}
    file_level: set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return per_line, file_level
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        kind, id_blob = m.group(1), m.group(2)
        ids = {
            part.strip().upper()
            for part in id_blob.replace(" ", ",").split(",")
            if part.strip()
        }
        ids = {("all" if i == "ALL" else i) for i in ids}
        if kind == "disable-file":
            file_level |= ids
            continue
        line = tok.start[0]
        per_line.setdefault(line, set()).update(ids)
        standalone = tok.line[: tok.start[1]].strip() == ""
        if standalone:
            # apply to the next code line, skipping continuation comment
            # lines and blanks (justifications are encouraged to run long)
            src_lines = src.splitlines()
            nxt = line  # 0-based index of the line after the comment
            while nxt < len(src_lines) and (
                not src_lines[nxt].strip()
                or src_lines[nxt].lstrip().startswith("#")
            ):
                nxt += 1
            per_line.setdefault(nxt + 1, set()).update(ids)
    return per_line, file_level


class _Scope:
    """Name -> FunctionDef bindings for one lexical scope."""

    def __init__(self) -> None:
        self.defs: dict[str, ast.AST] = {}


class _FileAnalysis:
    def __init__(self, src: str, path: str) -> None:
        self.src = src
        self.path = path
        self.tree = ast.parse(src)
        self.violations: list[Violation] = []
        self._annotate_parents()
        self._collect_imports()
        self._collect_scopes()
        self._collect_jit_contexts()

    # ---- plumbing ---------------------------------------------------------
    def _annotate_parents(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._sheeplint_parent = node  # type: ignore[attr-defined]

    def _parents(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = getattr(node, "_sheeplint_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_sheeplint_parent", None)

    def _collect_imports(self) -> None:
        """alias -> canonical dotted module/name, for `_dotted` substitution."""
        self.aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self.np_roots = {
            alias
            for alias, full in self.aliases.items()
            if full == "numpy" or full.startswith("numpy.")
        } | ({"numpy"} if "numpy" not in self.aliases else set())
        self.jnp_roots = {
            alias for alias, full in self.aliases.items() if full == "jax.numpy"
        }

    def _dotted(self, node: ast.AST) -> Optional[str]:
        """Literal dotted path with import aliases substituted at the root."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.aliases.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def _collect_scopes(self) -> None:
        """Per-scope function-def bindings for Name -> def resolution."""
        self.scope_of: dict[ast.AST, _Scope] = {}

        def visit(owner: ast.AST) -> None:
            scope = _Scope()
            self.scope_of[owner] = scope
            for node in _scope_children(owner):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scope.defs[node.name] = node
                    visit(node)
                elif isinstance(node, ast.Lambda):
                    visit(node)

        visit(self.tree)

    def _resolve_func(self, name_node: ast.expr, at: ast.AST) -> Optional[ast.AST]:
        if isinstance(name_node, ast.Lambda):
            return name_node
        if not isinstance(name_node, ast.Name):
            return None
        for owner in (at, *self._parents(at)):
            scope = self.scope_of.get(owner)
            if scope and name_node.id in scope.defs:
                return scope.defs[name_node.id]
        return None

    # ---- jit-context discovery -------------------------------------------
    def _transform_kind(self, dotted: Optional[str]) -> Optional[str]:
        if not dotted:
            return None
        leaf = dotted.rsplit(".", 1)[-1]
        if dotted in ("jax.jit", "jit") or leaf == "donating_jit":
            return "jit"
        root = dotted.split(".", 1)[0]
        if leaf in _WRAP_TRANSFORMS and root in ("jax", "eqx"):
            return "jit"
        if leaf in _BODY_ARG_TRANSFORMS and (
            root in ("jax", "lax", "checkify")
            or ".lax." in dotted
            or dotted.startswith("jax.")
            or "checkify" in dotted
        ):
            return leaf
        return None

    def _jit_like_call(self, call: ast.Call) -> bool:
        """True for `jax.jit(...)`, `donating_jit(...)`, and
        `partial(jax.jit, ...)` forms (the closure builders)."""
        kind = self._transform_kind(self._dotted(call.func))
        if kind == "jit":
            return True
        d = self._dotted(call.func)
        if d and d.rsplit(".", 1)[-1] == "partial":
            return any(
                self._transform_kind(self._dotted(a)) == "jit" for a in call.args
            )
        return False

    def _collect_jit_contexts(self) -> None:
        self.jit_contexts: set[ast.AST] = set()
        # decorated defs
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    if self._jit_like_call(dec):
                        self.jit_contexts.add(node)
                elif self._transform_kind(self._dotted(dec)) == "jit":
                    self.jit_contexts.add(node)
        # callables passed to transforms
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = self._dotted(node.func)
            kind = self._transform_kind(dotted)
            if kind == "jit" or (
                kind is None and self._jit_like_call(node)
            ):
                for arg in node.args[:1]:
                    fn = self._resolve_func(arg, node)
                    if fn is not None:
                        self.jit_contexts.add(fn)
            elif kind in _BODY_ARG_TRANSFORMS:
                for idx in _BODY_ARG_TRANSFORMS[kind]:
                    if idx < len(node.args):
                        fn = self._resolve_func(node.args[idx], node)
                        if fn is not None:
                            self.jit_contexts.add(fn)
            # lax.switch: list of branch callables
            if dotted and dotted.rsplit(".", 1)[-1] == "switch" and len(node.args) > 1:
                branches = node.args[1]
                if isinstance(branches, (ast.List, ast.Tuple)):
                    for el in branches.elts:
                        fn = self._resolve_func(el, node)
                        if fn is not None:
                            self.jit_contexts.add(fn)

    def _in_jit_context(self, node: ast.AST) -> bool:
        if node in self.jit_contexts:
            return True
        return any(p in self.jit_contexts for p in self._parents(node))

    def _top_level_contexts(self) -> list[ast.AST]:
        return [c for c in self.jit_contexts if not any(
            p in self.jit_contexts for p in self._parents(c)
        )]

    # ---- reporting --------------------------------------------------------
    def report(self, rule_id: str, node: ast.AST, message: str) -> None:
        self.violations.append(
            Violation(
                rule=RULES[rule_id],
                path=self.path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
            )
        )


def _scope_children(owner: ast.AST) -> Iterable[ast.AST]:
    """All descendants of `owner` that belong to its scope (stop at nested
    function/lambda boundaries, which own their own scope)."""
    body = (
        owner.body
        if not isinstance(owner, ast.Lambda)
        else [owner.body]
    ) if not isinstance(owner, ast.Module) else owner.body
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        return all(_is_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    return False


# ---------------------------------------------------------------------------
# Rule passes
# ---------------------------------------------------------------------------


def _check_sl001(a: _FileAnalysis) -> None:
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Call):
            continue
        donating = any(
            kw.arg in ("donate_argnums", "donate_argnames") for kw in node.keywords
        )
        if not donating:
            continue
        d = a._dotted(node.func)
        if d is None:
            continue
        leaf = d.rsplit(".", 1)[-1]
        if leaf == "donating_jit":
            continue
        if d in ("jax.jit", "jit") or (leaf == "jit" and d.startswith("jax")):
            a.report(
                "SL001", node,
                "bare jax.jit with donate_argnums (heap-corruption class on "
                "deserialized XLA:CPU executables)",
            )
        elif leaf == "partial" and any(
            a._transform_kind(a._dotted(arg)) == "jit"
            and a._dotted(arg) != "donating_jit"
            and not (a._dotted(arg) or "").endswith(".donating_jit")
            for arg in node.args
        ):
            a.report(
                "SL001", node,
                "partial(jax.jit, donate_argnums=...) outside donating_jit",
            )


def _iter_host_syncs(a: _FileAnalysis, ctx: ast.AST):
    """Yield `(call_node, kind, label)` for every blocking host-sync call
    under `ctx` — the shared detector behind SL002 (syncs traced inside a
    jit body) and SL007 (syncs on a hot-loop body's critical path). Kinds:
    `method` (.item()/.tolist()/.block_until_ready()), `np`
    (np.asarray/np.array on a non-literal), `device_get`,
    `block_until_ready` (the jax.* function form), `cast`
    (float()/int()/bool() on a non-shape expression)."""
    for node in ast.walk(ctx):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _HOST_SYNC_METHODS:
            yield node, "method", f".{func.attr}()"
            continue
        d = a._dotted(func)
        if d is not None:
            root, _, leaf = d.rpartition(".")
            if root in a.np_roots and leaf in ("asarray", "array") and node.args:
                if not _is_literal(node.args[0]):
                    yield node, "np", f"{root}.{leaf}()"
                continue
            if d == "jax.device_get":
                yield node, "device_get", "jax.device_get"
                continue
            if d == "jax.block_until_ready":
                yield node, "block_until_ready", "jax.block_until_ready"
                continue
        if (
            isinstance(func, ast.Name)
            and func.id in ("float", "int", "bool")
            and len(node.args) == 1
            and not _is_literal(node.args[0])
        ):
            arg = node.args[0]
            shapeish = _contains(
                arg,
                lambda n: (
                    isinstance(n, ast.Attribute) and n.attr in _SHAPE_ATTRS
                ) or (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and n.func.id == "len"
                ),
            )
            if not shapeish:
                yield node, "cast", f"{func.id}()"


def _check_sl002(a: _FileAnalysis, ctx: ast.AST) -> None:
    for node, kind, label in _iter_host_syncs(a, ctx):
        if kind == "method":
            msg = f"{label} on a traced value inside a jit/scan/vmap body"
        elif kind == "np":
            msg = (
                f"{label} materializes a traced value on host "
                "inside a jit/scan/vmap body"
            )
        elif kind in ("device_get", "block_until_ready"):
            msg = f"{label} inside a jit/scan/vmap body"
        else:
            msg = (
                f"{label} forces a device->host sync on a traced value "
                "inside a jit/scan/vmap body"
            )
        a.report("SL002", node, msg)


def _check_sl003(a: _FileAnalysis, ctx: ast.AST) -> None:
    def tracerish(expr: ast.AST) -> bool:
        def pred(n: ast.AST) -> bool:
            if not isinstance(n, ast.Call):
                return False
            d = a._dotted(n.func)
            if d is not None and d.split(".", 1)[0] in a.jnp_roots:
                return True
            if d is not None and d.startswith("jax.numpy."):
                return True
            return (
                isinstance(n.func, ast.Attribute)
                and n.func.attr in ("any", "all")
                and not n.args
            )
        return _contains(expr, pred)

    for node in ast.walk(ctx):
        if isinstance(node, (ast.If, ast.While)) and tracerish(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            a.report(
                "SL003", node,
                f"Python `{kind}` on a traced array expression inside a "
                "jit/scan/vmap body (use lax.cond/lax.while_loop/lax.select)",
            )
        elif isinstance(node, ast.Assert) and tracerish(node.test):
            a.report(
                "SL003", node,
                "Python `assert` on a traced array inside a jit/scan/vmap "
                "body (use checkify.check)",
            )


def _check_sl004(a: _FileAnalysis) -> None:
    # (a) jit closure built inside a loop: every iteration pays a fresh trace
    for node in ast.walk(a.tree):
        if not (isinstance(node, ast.Call) and a._jit_like_call(node)):
            continue
        for p in a._parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
            if isinstance(p, (ast.For, ast.While)):
                a.report(
                    "SL004", node,
                    "jit closure built inside a loop body — hoist it so the "
                    "executable is compiled once, not per iteration",
                )
                break
    # (b) static_argnums naming a parameter with a mutable (unhashable) default
    for node in ast.walk(a.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        static_nums: list[int] = []
        for dec in node.decorator_list:
            if not (isinstance(dec, ast.Call) and a._jit_like_call(dec)):
                continue
            for kw in dec.keywords:
                if kw.arg == "static_argnums" and isinstance(
                    kw.value, (ast.Constant, ast.Tuple)
                ):
                    vals = (
                        [kw.value.value]
                        if isinstance(kw.value, ast.Constant)
                        else [
                            e.value
                            for e in kw.value.elts
                            if isinstance(e, ast.Constant)
                        ]
                    )
                    static_nums.extend(v for v in vals if isinstance(v, int))
        if not static_nums:
            continue
        params = node.args.args
        defaults = node.args.defaults
        offset = len(params) - len(defaults)
        for num in static_nums:
            if num < offset or num >= len(params):
                continue
            default = defaults[num - offset]
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                a.report(
                    "SL004", node,
                    f"static_argnums includes `{params[num].arg}` whose "
                    "default is unhashable — every call raises or retraces",
                )


def _check_sl005(a: _FileAnalysis) -> None:
    registered: set[str] = set()
    for node in ast.walk(a.tree):
        if isinstance(node, ast.Call):
            d = a._dotted(node.func)
            leaf = (d or "").rsplit(".", 1)[-1]
            if leaf in (
                "register_pytree_node",
                "register_pytree_with_keys",
                "register_dataclass",
                "register_static",
            ) and node.args and isinstance(node.args[0], ast.Name):
                registered.add(node.args[0].id)
    # names referenced inside any jit context
    referenced: set[str] = set()
    for ctx in a._top_level_contexts():
        for node in ast.walk(ctx):
            if isinstance(node, ast.Name):
                referenced.add(node.id)
        if isinstance(ctx, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for arg in (*ctx.args.args, *ctx.args.kwonlyargs):
                ann = arg.annotation
                if isinstance(ann, ast.Name):
                    referenced.add(ann.id)
                elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    referenced.add(ann.value)
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        is_dataclass = False
        for dec in node.decorator_list:
            d = a._dotted(dec.func if isinstance(dec, ast.Call) else dec)
            if d and d.rsplit(".", 1)[-1] == "dataclass":
                is_dataclass = True
            if d and d.rsplit(".", 1)[-1] == "register_pytree_node_class":
                registered.add(node.name)
        if not is_dataclass:
            continue
        bases = [a._dotted(b) for b in node.bases]
        if any(b not in (None, "object") for b in bases) or (
            node.bases and any(b is None for b in bases)
        ):
            continue  # a base class (e.g. nn.Module) may auto-register
        if node.name in registered or node.name not in referenced:
            continue
        a.report(
            "SL005", node,
            f"@dataclass `{node.name}` is used inside jitted code but never "
            "registered with jax.tree_util",
        )


def _check_sl006(a: _FileAnalysis) -> None:
    if "parallel" not in Path(a.path).parts:
        return
    shardish = (
        "NamedSharding", "PartitionSpec", "shard_map", "device_put_sharded",
    )
    for ctx in a._top_level_contexts():
        touches, constrained = False, False
        for node in ast.walk(ctx):
            if isinstance(node, ast.Name) and node.id in shardish:
                touches = True
            elif isinstance(node, ast.Attribute) and node.attr in shardish:
                touches = True
            if isinstance(node, ast.Call):
                d = a._dotted(node.func) or ""
                leaf = d.rsplit(".", 1)[-1]
                if leaf in ("with_sharding_constraint", "constrain"):
                    constrained = True
        if touches and not constrained:
            a.report(
                "SL006", ctx,
                "jitted function builds shardings but never applies "
                "with_sharding_constraint — layout is left to GSPMD",
            )


_HOTLOOP_NAME_RE = re.compile(r"^_?(one_(cycle|step|update)|\w*hot_?loop\w*)$")
_HOTLOOP_MARK_RE = re.compile(r"sheeplint:\s*hotloop")


def _hotloop_marked_lines(src: str) -> set[int]:
    """Lines carrying a `# sheeplint: hotloop` marker — the explicit way to
    declare a function a hot-loop body when its name does not say so."""
    marked: set[int] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT and _HOTLOOP_MARK_RE.search(tok.string):
                marked.add(tok.start[0])
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return marked


def _check_sl007(a: _FileAnalysis) -> None:
    """Blocking host syncs on a hot-loop body's critical path. A function is
    a hot-loop body when its NAME says so (one_cycle / one_step / one_update
    / *hot_loop*) or a `# sheeplint: hotloop` marker sits on/above its def.
    Syncs inside jit bodies are SL002's jurisdiction and skipped here."""
    marked = _hotloop_marked_lines(a.src)
    for node in ast.walk(a.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        anchor_lines = {node.lineno, node.lineno - 1}
        for dec in node.decorator_list:
            anchor_lines |= {dec.lineno, dec.lineno - 1}
        hot = bool(_HOTLOOP_NAME_RE.match(node.name)) or bool(
            anchor_lines & marked
        )
        if not hot or a._in_jit_context(node):
            continue
        for call, _, label in _iter_host_syncs(a, node):
            if any(p in a.jit_contexts for p in a._parents(call)):
                continue  # traced body: SL002 reports it
            a.report(
                "SL007", call,
                f"{label} blocks hot-loop body `{node.name}` — defer the "
                "pull (parallel/pipeline.py) or move it off the loop",
            )


def _callback_label(a: _FileAnalysis, node: ast.Call) -> Optional[str]:
    """The dotted name when `node` calls a host-callback entry point
    (jax.debug.print/callback, io_callback, pure_callback, host_callback)."""
    d = a._dotted(node.func)
    if d is None:
        return None
    root, _, leaf = d.rpartition(".")
    if leaf not in _CALLBACK_LEAVES:
        return None
    if leaf in ("io_callback", "pure_callback"):
        return d  # distinctive names; aliases already resolved to jax paths
    if leaf in ("print", "callback") and "debug" in root.split("."):
        return d
    if leaf in ("call", "id_tap") and "host_callback" in root:
        return d
    return None


def _check_sl008(a: _FileAnalysis) -> None:
    """Host callbacks traced into HOT jit/scan bodies. SL002 flags blocking
    syncs anywhere in traced code; callbacks are non-blocking-looking (they
    trace fine and run "async") which is exactly why a `jax.debug.print`
    left in an Anakin scan body survives review — at dispatch it costs one
    host round-trip PER SCAN ITERATION. Scope: only bodies marked
    `# sheeplint: hotloop` or named like hot loops, so intentional
    callbacks elsewhere stay lintable by sheepcheck SC002 instead."""
    marked = _hotloop_marked_lines(a.src)
    reported: set[int] = set()
    for ctx in a.jit_contexts:
        if not isinstance(ctx, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        anchor = {ctx.lineno, ctx.lineno - 1}
        for dec in ctx.decorator_list:
            anchor |= {dec.lineno, dec.lineno - 1}
        hot = bool(_HOTLOOP_NAME_RE.match(ctx.name)) or bool(anchor & marked)
        if not hot:
            continue
        for node in ast.walk(ctx):
            if not isinstance(node, ast.Call) or id(node) in reported:
                continue
            label = _callback_label(a, node)
            if label:
                reported.add(id(node))
                a.report(
                    "SL008", node,
                    f"`{label}` traced into hot-loop body `{ctx.name}` — "
                    "every scan iteration pays a host round-trip",
                )


def _is_numeric_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, (ast.USub, ast.UAdd)):
        node = node.operand
    return (
        isinstance(node, ast.Constant)
        and type(node.value) in (int, float)  # bools are static flags, skip
    )


def _collect_jit_bound(a: _FileAnalysis) -> tuple[set[str], set[tuple[str, object]]]:
    """Names (and `dict[key]` slots) assigned from jit-building calls:
    `x = jax.jit(...)`, `x = donating_jit(...)`, `x = plan.register(...)`,
    `jits["critic"] = plan.register(...)`."""
    names: set[str] = set()
    subs: set[tuple[str, object]] = set()
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        jity = a._jit_like_call(call)
        if not jity:
            d = a._dotted(call.func)
            if (
                d
                and d.rsplit(".", 1)[-1] == "register"
                and "plan" in d.rsplit(".", 1)[0].lower()
            ):
                jity = True
        if not jity:
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and isinstance(t.slice, ast.Constant)
            ):
                subs.add((t.value.id, t.slice.value))
    return names, subs


def _check_sl009(a: _FileAnalysis) -> None:
    """Bare Python numeric constants passed to jit-bound callables. The
    scalar enters the jit as a WEAK-typed 0-d array: mixing such a call
    site with one passing `jnp.float32(x)` retraces the whole executable
    (weak vs strong avals are different cache keys), and every call pays an
    implicit host->device put of the constant — the exact gamma/lambda
    class --sanitize caught in PR 2."""
    names, subs = _collect_jit_bound(a)
    if not names and not subs:
        return
    for node in ast.walk(a.tree):
        if not isinstance(node, ast.Call):
            continue
        target: Optional[str] = None
        args: list[ast.expr] = list(node.args)
        f = node.func
        if isinstance(f, ast.Name) and f.id in names:
            target = f.id
        elif (
            isinstance(f, ast.Subscript)
            and isinstance(f.value, ast.Name)
            and isinstance(f.slice, ast.Constant)
            and (f.value.id, f.slice.value) in subs
        ):
            target = f"{f.value.id}[{f.slice.value!r}]"
        else:
            # sanitizer.checked("phase", jit_w, *args) forwards to the jit
            d = a._dotted(f)
            if (
                d
                and d.rsplit(".", 1)[-1] == "checked"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Name)
                and node.args[1].id in names
            ):
                target = node.args[1].id
                args = list(node.args[2:])
        if target is None:
            continue
        for arg in (*args, *(kw.value for kw in node.keywords)):
            if _is_numeric_literal(arg):
                a.report(
                    "SL009", arg,
                    f"bare numeric constant `{ast.unparse(arg)}` passed to "
                    f"jitted `{target}` — enters as a weak-typed scalar "
                    "(retrace hazard + per-call h2d put); wrap once as "
                    "jnp.float32(...)",
                )


_SL010_BATCHISH_RE = re.compile(
    r"(?:^|[^A-Za-z0-9_])_?(?:batch|batched|global_batch|sample|samples|"
    r"rollout|rollouts|traj|trajectory|windows|transitions|rb|replay|"
    r"buffer|buffers|data)(?:[^A-Za-z0-9]|_batch|$)"
)
# helpers that ARE the explicit-sharding path: a value handed to one of
# these downstream is committed properly, so its construction site is clean
_SL010_SHARD_HELPERS = {
    "shard_batch", "shard_time_batch", "shard_env_batch", "to_trainers",
}
_SL010_MESH_BUILDERS = {"make_mesh", "build_mesh", "Mesh", "create_device_mesh"}


def _check_sl010(a: _FileAnalysis) -> None:
    """Unsharded puts of batch-sized values in mesh-aware host code. A bare
    `jnp.asarray(batch)` / one-arg `jax.device_put(batch)` in a function
    that builds or holds a mesh lands the batch UNCOMMITTED on the default
    device: sharded consumers then replicate or single-device it silently —
    the host-side twin of sheepshard SC007. Scope: only batch-shaped names
    (replay reads, sample/rollout/batch/data values); a value the same
    function later routes through shard_batch / shard_time_batch /
    shard_env_batch / to_trainers is the explicit-sharding idiom and
    exempt."""

    def fn_of(node: ast.AST) -> ast.AST:
        for p in a._parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return p
        return a.tree

    meshy: set[ast.AST] = set()
    sharded_names: dict[ast.AST, set[str]] = {}
    for node in ast.walk(a.tree):
        if isinstance(node, ast.Name) and node.id in ("mesh", "meshes"):
            meshy.add(fn_of(node))
        elif isinstance(node, ast.Call):
            d = a._dotted(node.func) or ""
            leaf = d.rsplit(".", 1)[-1]
            if leaf in _SL010_MESH_BUILDERS:
                meshy.add(fn_of(node))
            if (
                leaf in _SL010_SHARD_HELPERS
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                sharded_names.setdefault(fn_of(node), set()).add(node.args[0].id)
    if not meshy:
        return

    for node in ast.walk(a.tree):
        if not (isinstance(node, ast.Call) and node.args):
            continue
        d = a._dotted(node.func)
        if d is None:
            continue
        root, _, leaf = d.rpartition(".")
        is_put = (
            d == "jax.device_put"
            and len(node.args) == 1
            and not any(kw.arg in ("device", "sharding") for kw in node.keywords)
        )
        is_asarray = leaf == "asarray" and root == "jax.numpy"
        if not (is_put or is_asarray):
            continue
        if a._in_jit_context(node):
            continue  # in-jit constants are SC-rule jurisdiction
        owner = fn_of(node)
        if owner not in meshy:
            continue
        # batch-shaped? match the argument text, plus the iterables of any
        # enclosing comprehension (`{k: jnp.asarray(v) for k, v in
        # sample.items()}` — the batch name lives on the generator)
        pool = [ast.unparse(node.args[0])]
        for p in a._parents(node):
            if isinstance(
                p, (ast.DictComp, ast.ListComp, ast.SetComp, ast.GeneratorExp)
            ):
                pool.extend(ast.unparse(g.iter) for g in p.generators)
            elif isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
        if not _SL010_BATCHISH_RE.search(" ".join(pool)):
            continue
        # explicit-sharding idiom: the nearest enclosing assignment's target
        # is later handed to a shard helper in the same function
        target: Optional[str] = None
        for p in a._parents(node):
            if isinstance(p, ast.Assign):
                for t in p.targets:
                    if isinstance(t, ast.Name):
                        target = t.id
                break
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                break
        if target is not None and target in sharded_names.get(owner, set()):
            continue
        label = "jax.device_put" if is_put else f"{d.rsplit('.', 1)[0]}.asarray"
        a.report(
            "SL010", node,
            f"`{label}` of a batch-sized value in mesh-aware host code "
            "without an explicit sharding — the put lands uncommitted on "
            "the default device and sharded consumers silently replicate "
            "or single-device it (host-side twin of sheepshard SC007)",
        )


# array constructors whose module-level result is an ndarray constant —
# closing over one from a jit body bakes it into every compiled executable
# (the sheepmem SC012 class, caught here before trace time)
_SL011_BUILDER_LEAVES = {
    "array", "asarray", "zeros", "ones", "full", "arange", "linspace",
    "logspace", "eye", "identity", "tri", "diag", "stack", "concatenate",
    "meshgrid", "load", "loadtxt", "fromfunction", "frombuffer",
}


def _check_sl011(a: _FileAnalysis) -> None:
    """Module-level ndarray constants referenced inside jit bodies. Only
    names ASSIGNED at module scope from a numpy/jax.numpy array constructor
    count — imported names, scalars, and locals are out of scope, so what
    this catches is near-certainly a baked-in executable constant."""
    globals_: dict[str, str] = {}
    for node in a.tree.body:
        targets: list[ast.expr] = []
        value: ast.AST | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if not isinstance(value, ast.Call):
            continue
        d = a._dotted(value.func)
        if d is None:
            continue
        root, _, leaf = d.rpartition(".")
        root_head = root.split(".", 1)[0]
        is_builder = leaf in _SL011_BUILDER_LEAVES and (
            root_head in a.np_roots
            or root_head in a.jnp_roots
            or root.startswith(("numpy", "jax.numpy"))
        )
        if not is_builder:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                globals_[t.id] = d
    if not globals_:
        return
    reported: set[tuple[int, str]] = set()
    for ctx in a._top_level_contexts():
        # names bound locally anywhere under the context (params, assigns,
        # comprehension vars) shadow the module constant
        local: set[str] = set()
        for node in ast.walk(ctx):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                for p in (
                    *args.posonlyargs, *args.args, *args.kwonlyargs,
                    *filter(None, (args.vararg, args.kwarg)),
                ):
                    local.add(p.arg)
            elif isinstance(node, ast.Lambda):
                for p in (*node.args.args, *node.args.kwonlyargs):
                    local.add(p.arg)
            elif isinstance(node, (ast.Name, ast.Global)) and (
                isinstance(node, ast.Global)
                or isinstance(node.ctx, ast.Store)
            ):
                local.update(
                    node.names if isinstance(node, ast.Global) else [node.id]
                )
        for node in ast.walk(ctx):
            if not (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id in globals_
                and node.id not in local
            ):
                continue
            key = (node.lineno, node.id)
            if key in reported:
                continue
            reported.add(key)
            owner = getattr(ctx, "name", "<lambda>")
            a.report(
                "SL011", node,
                f"jitted `{owner}` closes over module-level ndarray "
                f"`{node.id}` (= {globals_[node.id]}(...)) — baked into "
                "every compiled executable as an embedded constant; pass "
                "it as an argument instead",
            )


_SL012_BROAD = {"Exception", "BaseException"}


def _check_sl012(a: _FileAnalysis) -> None:
    """Swallowed-and-unlogged broad exception handlers (ISSUE 12): a bare
    `except:` / `except Exception:` / `except BaseException:` whose body is
    nothing but pass/.../continue/break. Narrow handlers are presumed
    deliberate; broad ones that also swallow silently leave no forensic
    trail when an env, checkpoint or transfer dies — the exact class the
    resilience subsystem's telemetry events exist to record."""

    def is_broad(handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        elems = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elems:
            leaf = e.attr if isinstance(e, ast.Attribute) else getattr(e, "id", None)
            if leaf in _SL012_BROAD:
                return True
        return False

    def swallows(stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            return True
        return (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis
        )

    for node in ast.walk(a.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not is_broad(node):
            continue
        if all(swallows(s) for s in node.body):
            caught = "bare except" if node.type is None else ast.unparse(node.type)
            a.report(
                "SL012", node,
                f"broad handler ({caught}) swallows the exception with no "
                "log, event or re-raise — narrow the type or record the "
                "failure (telemetry event / Fault counter / logger)",
            )


_SL013_SEND_SINKS = {"send", "sendall", "sendto", "send_bytes"}
_SL013_HOST_PULLS = {"asarray", "ascontiguousarray", "array"}


def _check_sl013(a: _FileAnalysis) -> None:
    """Device arrays reaching serialization/socket sinks (ISSUE 14): a name
    assigned from a jax.*/jnp.* call is device-tainted; passing it (or a
    view/slice of it) to .tobytes(), socket send*/send_bytes or
    pickle.dump/dumps hides a blocking d2h transfer inside the sink. An
    explicit host pull (np.asarray/np.ascontiguousarray/np.array/
    jax.device_get/bytes) clears the taint. Statements are processed in
    source order per scope, so rebinding through a pull untaints."""

    def _call_dotted(call: ast.Call) -> Optional[str]:
        return a._dotted(call.func)

    def is_host_pull(call: ast.Call) -> bool:
        d = _call_dotted(call)
        if not d:
            return False
        root, leaf = d.split(".", 1)[0], d.rsplit(".", 1)[-1]
        if root in a.np_roots and leaf in _SL013_HOST_PULLS:
            return True
        if leaf == "device_get":
            return True
        return d in ("bytes", "memoryview", "bytearray")

    def is_device_call(call: ast.Call) -> bool:
        d = _call_dotted(call)
        if not d:
            return False
        root = d.split(".", 1)[0]
        if is_host_pull(call):
            return False
        return (
            root == "jax"
            or root in a.jnp_roots
            or d.startswith("jax.numpy")
        )

    def tainted(node: ast.AST, taint: set) -> bool:
        """Does this expression carry a device value? Follows views
        (slices/attributes/arithmetic), stops at host pulls."""
        if isinstance(node, ast.Name):
            return node.id in taint
        if isinstance(node, ast.Call):
            return is_device_call(node)
        if isinstance(node, ast.BinOp):
            return tainted(node.left, taint) or tainted(node.right, taint)
        if isinstance(node, (ast.Subscript, ast.Attribute, ast.Starred)):
            return tainted(node.value, taint)
        return False

    def scan(node: ast.AST, taint: set) -> None:
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            if isinstance(f, ast.Attribute):
                if f.attr == "tobytes" and tainted(f.value, taint):
                    a.report(
                        "SL013", n,
                        f"`{ast.unparse(f.value)}.tobytes()` serializes a "
                        "device array — the byte view is a hidden blocking "
                        "d2h transfer; pull with np.asarray first",
                    )
                    continue
                if f.attr in _SL013_SEND_SINKS:
                    for arg in n.args:
                        if tainted(arg, taint):
                            a.report(
                                "SL013", n,
                                f"device array `{ast.unparse(arg)}` passed "
                                f"to socket .{f.attr}() without an explicit "
                                "host pull",
                            )
                    continue
            d = _call_dotted(n)
            if d and d.rsplit(".", 1)[-1] in ("dump", "dumps") and (
                "pickle" in d
            ):
                for arg in n.args:
                    if tainted(arg, taint):
                        a.report(
                            "SL013", n,
                            f"device array `{ast.unparse(arg)}` passed to "
                            f"{d} without an explicit host pull",
                        )

    def bind(target: ast.expr, is_tainted: bool, taint: set) -> None:
        names = (
            [target]
            if isinstance(target, ast.Name)
            else list(getattr(target, "elts", []))
        )
        for nm in names:
            if isinstance(nm, ast.Starred):
                nm = nm.value
            if isinstance(nm, ast.Name):
                (taint.add if is_tainted else taint.discard)(nm.id)

    def run(stmts, taint: set) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                run(s.body, set())
                continue
            if isinstance(s, ast.ClassDef):
                run(s.body, set())
                continue
            if isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = s.value
                if value is None:
                    continue
                scan(value, taint)
                t = tainted(value, taint)
                targets = s.targets if isinstance(s, ast.Assign) else [s.target]
                for tgt in targets:
                    bind(tgt, t, taint)
                continue
            bodies = []
            for field in ("body", "orelse", "finalbody"):
                bodies.extend(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                bodies.extend(h.body)
            if bodies:
                # scan the statement's own expressions (test/iter/items)
                for field, val in ast.iter_fields(s):
                    if field in ("body", "orelse", "finalbody", "handlers"):
                        continue
                    for v in val if isinstance(val, list) else [val]:
                        if isinstance(v, ast.withitem):
                            scan(v.context_expr, taint)
                        elif isinstance(v, ast.expr):
                            scan(v, taint)
                if isinstance(s, ast.For):
                    bind(s.target, tainted(s.iter, taint), taint)
                run(bodies, taint)
            else:
                scan(s, taint)

    run(a.tree.body, set())


def _check_sl014(a: _FileAnalysis) -> None:
    """Anonymous threads (ISSUE 18): a direct `threading.Thread(...)` call
    must pass BOTH `name=` (sheeptrace/sheepsync attribution is keyed by
    thread name) and `daemon=` (the inherited flag makes shutdown behavior
    an accident of the spawning thread). `threading.Timer(...)` takes no
    daemon kwarg, so its stored handle needs a `.daemon =` assignment in
    the same scope before `start()`. Thread *subclass* constructions are
    exempt — the subclass' own __init__ (a `super().__init__(...)` call,
    which `_dotted` cannot resolve anyway) makes the decision once."""
    # scope -> names Timer handles are stored under / names with .daemon set
    timer_stores: dict[ast.AST, list[tuple[ast.Call, str]]] = {}
    daemon_sets: dict[ast.AST, set[str]] = {}

    def scope_of(node: ast.AST) -> ast.AST:
        for p in a._parents(node):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return p
        return a.tree

    def store_name(target: ast.expr) -> Optional[str]:
        # `t = Timer(...)` and `self._timer = Timer(...)` both count
        if isinstance(target, ast.Name):
            return target.id
        if isinstance(target, ast.Attribute):
            return a._dotted(target)
        return None

    for n in ast.walk(a.tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            tgt = n.targets[0]
            if (
                isinstance(tgt, ast.Attribute)
                and tgt.attr == "daemon"
                and (base := a._dotted(tgt.value)) is not None
            ):
                daemon_sets.setdefault(scope_of(n), set()).add(base)
            if isinstance(n.value, ast.Call):
                d = a._dotted(n.value.func)
                if d in ("threading.Timer", "Timer") and (
                    nm := store_name(tgt)
                ):
                    timer_stores.setdefault(scope_of(n), []).append(
                        (n.value, nm)
                    )
        if not isinstance(n, ast.Call):
            continue
        d = a._dotted(n.func)
        if d in ("threading.Thread", "Thread"):
            kwargs = {kw.arg for kw in n.keywords}
            missing = [k for k in ("name", "daemon") if k not in kwargs]
            if missing:
                a.report(
                    "SL014", n,
                    "threading.Thread constructed without explicit "
                    f"{' or '.join(f'`{m}=`' for m in missing)} — unnamed "
                    "threads break sheeptrace/sheepsync attribution and an "
                    "inherited daemon flag makes shutdown behavior an "
                    "accident of the spawner",
                )

    for scope, stores in timer_stores.items():
        have = daemon_sets.get(scope, set())
        for call, nm in stores:
            if nm not in have:
                a.report(
                    "SL014", call,
                    f"threading.Timer stored as `{nm}` never gets a "
                    "`.daemon =` decision in this scope — set "
                    f"`{nm}.daemon = True` (or False) before start()",
                )


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def lint_source(
    src: str, path: str = "<string>", select: Optional[set[str]] = None
) -> list[Violation]:
    try:
        analysis = _FileAnalysis(src, path)
    except SyntaxError as exc:
        raise ValueError(f"{path}: cannot parse: {exc}") from exc
    _check_sl001(analysis)
    _check_sl004(analysis)
    _check_sl005(analysis)
    _check_sl006(analysis)
    _check_sl007(analysis)
    _check_sl008(analysis)
    _check_sl009(analysis)
    _check_sl010(analysis)
    _check_sl011(analysis)
    _check_sl012(analysis)
    _check_sl013(analysis)
    _check_sl014(analysis)
    for ctx in analysis._top_level_contexts():
        _check_sl002(analysis, ctx)
        _check_sl003(analysis, ctx)
    per_line, file_level = _parse_suppressions(src)
    out = []
    for v in analysis.violations:
        if select is not None and v.rule.id not in select:
            continue
        if "all" in file_level or v.rule.id in file_level:
            continue
        line_sup = per_line.get(v.line, set())
        if "all" in line_sup or v.rule.id in line_sup:
            continue
        out.append(v)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule.id))
    return out


def lint_file(path: str, select: Optional[set[str]] = None) -> list[Violation]:
    with open(path, encoding="utf-8") as fh:
        return lint_source(fh.read(), path, select=select)


def iter_python_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isdir(p):
            for f in sorted(Path(p).rglob("*.py")):
                if "__pycache__" in f.parts:
                    continue
                yield str(f)
        else:
            yield p


def lint_paths(
    paths: Iterable[str], select: Optional[set[str]] = None
) -> list[Violation]:
    out: list[Violation] = []
    for f in iter_python_files(paths):
        out.extend(lint_file(f, select=select))
    return out
