"""Runtime thread sanitizer — the dynamic half of sheepsync (ISSUE 18).

`install()` replaces `threading.Lock` / `threading.RLock` /
`threading.Condition` with instrumented factories. Every lock allocated
afterwards records, per thread, the order it is acquired in, and every
acquisition is asserted against the **committed lock-order DAG** from
`analysis/budget/concurrency.json` plus the order observed so far in this
process:

  - acquiring B while holding A, when `B -> A` is a committed or
    already-observed edge, is a `sync.order_violation` telemetry event
    (the inversion that becomes a deadlock under the wrong interleaving);
  - an edge known to neither is counted as *undeclared* (gauge only —
    locks born outside the analyzed packages have no static identity);
  - hold times and contention (an acquire that had to block) are
    aggregated into `Sync/*` gauges.

Violations never raise and the wrappers preserve full Lock/RLock/
Condition semantics (`_is_owned`/`_release_save`/`_acquire_restore`
included, so `Condition.wait` works and correctly un-tracks the backing
lock while waiting). Overhead is a few dict operations per acquisition —
acceptable for tests and the chaos bench, not for production serving.

Lock naming: the allocation site (`path:line`) is matched against the
ledger's `lock_sites` table, so a lock allocated at
`sheeprl_tpu/flock/service.py:221` reports as
`flock.service.ReplayService._lock`; unmatched sites keep the raw
`path:line` name.

Enablement: `install()` directly (tests), `maybe_install_from_env()` off
`SHEEPRL_TPU_SANITIZE_THREADS=1` (the flock/serve suites, subprocess
actors, the serve main and the chaos bench export it), or the
`--sanitize_threads` run flag.
"""

from __future__ import annotations

import _thread
import os
import sys
import threading
import time
from pathlib import Path
from typing import Any, Optional

__all__ = [
    "ENV_VAR",
    "ThreadSanitizer",
    "gauges",
    "install",
    "installed",
    "maybe_install_from_env",
    "uninstall",
]

ENV_VAR = "SHEEPRL_TPU_SANITIZE_THREADS"

_REPO = Path(__file__).resolve().parents[2]

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition

_STATE: Optional["ThreadSanitizer"] = None


class _Held(threading.local):
    def __init__(self):
        self.stack: list = []  # innermost-last instrumented locks
        self.counts: dict = {}  # id(lock) -> recursion depth


class ThreadSanitizer:
    """Book-keeping shared by every instrumented lock in the process."""

    def __init__(self, ledger: Optional[dict] = None):
        conc = (ledger or {}).get("concurrency", {})
        self.sites: dict[str, str] = dict(conc.get("lock_sites", {}))
        edges = [tuple(e) for e in conc.get("lock_order", {}).get("edges", [])]
        self.committed: set[tuple[str, str]] = self._closure(edges)
        self.observed: set[tuple[str, str]] = set()
        self.violations: list[dict] = []
        self.acquisitions = 0
        self.contended = 0
        self.undeclared: set[tuple[str, str]] = set()
        self.hold_count = 0
        self.hold_total_ms = 0.0
        self.hold_max_ms = 0.0
        self.wait_max_ms = 0.0
        self._held = _Held()
        # internal guard: a RAW lock — instrumenting it would recurse
        self._meta = _real_lock()

    @staticmethod
    def _closure(edges) -> set:
        adj: dict[str, set[str]] = {}
        for a, b in edges:
            adj.setdefault(a, set()).add(b)
        out: set[tuple[str, str]] = set()
        for src in adj:
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            out.update((src, d) for d in seen if d != src)
        return out

    # -- naming ----------------------------------------------------------------

    def name_for_site(self) -> str:
        """Walk out of this module to the allocation frame and map it
        through the ledger's lock_sites table."""
        frame = sys._getframe(2)
        here = __file__
        while frame is not None and frame.f_code.co_filename == here:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        path = frame.f_code.co_filename
        try:
            rel = str(Path(path).resolve().relative_to(_REPO))
        except ValueError:
            rel = path
        site = f"{rel}:{frame.f_lineno}"
        return self.sites.get(site, site)

    # -- acquisition book-keeping ----------------------------------------------

    def note_acquire(self, lock: "_InstrumentedLock") -> None:
        held = self._held
        count = held.counts.get(id(lock), 0)
        held.counts[id(lock)] = count + 1
        if count:
            return  # reentrant RLock acquire: no new ordering information
        self.acquisitions += 1
        name = lock.sync_name
        for outer in held.stack:
            a = outer.sync_name
            if a == name:
                continue
            edge = (a, name)
            inverse = (name, a)
            if inverse in self.committed or inverse in self.observed:
                self._violation(a, name)
            elif edge not in self.committed:
                # any ordering the static ledger does not know about —
                # either a lock allocated outside the analyzed packages or
                # a genuinely new edge between known locks
                with self._meta:
                    self.undeclared.add(edge)
            with self._meta:
                self.observed.add(edge)
        held.stack.append(lock)
        lock.sync_acquired_at = time.monotonic()

    def note_release(self, lock: "_InstrumentedLock") -> None:
        held = self._held
        count = held.counts.get(id(lock), 0)
        if count > 1:
            held.counts[id(lock)] = count - 1
            return
        held.counts.pop(id(lock), None)
        try:
            held.stack.remove(lock)
        except ValueError:
            pass
        t0 = lock.sync_acquired_at
        if t0 is not None:
            ms = (time.monotonic() - t0) * 1000.0
            lock.sync_acquired_at = None
            with self._meta:
                self.hold_count += 1
                self.hold_total_ms += ms
                self.hold_max_ms = max(self.hold_max_ms, ms)

    def note_contention(self, lock: "_InstrumentedLock", waited_ms: float) -> None:
        with self._meta:
            self.contended += 1
            self.wait_max_ms = max(self.wait_max_ms, waited_ms)

    def drop_while_waiting(self, lock: "_InstrumentedLock") -> int:
        """Condition.wait path: fully un-track the backing lock; returns
        the saved recursion depth for restore."""
        held = self._held
        saved = held.counts.pop(id(lock), 0)
        try:
            held.stack.remove(lock)
        except ValueError:
            pass
        lock.sync_acquired_at = None
        return saved

    def restore_after_wait(self, lock: "_InstrumentedLock", saved: int) -> None:
        held = self._held
        self.note_acquire(lock)
        if saved > 1:
            held.counts[id(lock)] = saved

    def owned(self, lock: "_InstrumentedLock") -> bool:
        return self._held.counts.get(id(lock), 0) > 0

    def _violation(self, held_name: str, acquiring: str) -> None:
        record = {
            "acquiring": acquiring,
            "held": held_name,
            "thread": threading.current_thread().name,
            "ts": time.time(),
        }
        with self._meta:
            self.violations.append(record)
            if len(self.violations) > 200:
                del self.violations[: len(self.violations) - 200]
        self._emit("sync.order_violation", **record)

    @staticmethod
    def _emit(event: str, **data: Any) -> None:
        try:
            from ..telemetry import core as telemetry

            telemetry.emit(event, **data)
        # sheeplint: disable=SL012 — the sanitizer reports THROUGH telemetry;
        # a broken telemetry sink has nowhere better to report to
        except Exception:
            pass

    # -- views -----------------------------------------------------------------

    def gauges(self) -> dict[str, float]:
        avg = self.hold_total_ms / self.hold_count if self.hold_count else 0.0
        return {
            "Sync/acquisitions": float(self.acquisitions),
            "Sync/contended": float(self.contended),
            "Sync/order_violations": float(len(self.violations)),
            "Sync/undeclared_edges": float(len(self.undeclared)),
            "Sync/observed_edges": float(len(self.observed)),
            "Sync/hold_ms_avg": round(avg, 3),
            "Sync/hold_ms_max": round(self.hold_max_ms, 3),
            "Sync/wait_ms_max": round(self.wait_max_ms, 3),
        }

    def summary(self) -> dict:
        return {
            "violations": list(self.violations),
            "undeclared_edges": sorted(self.undeclared),
            "observed_edges": sorted(self.observed),
            **self.gauges(),
        }


class _InstrumentedLock:
    """Wraps a raw Lock or RLock; safe as a Condition backing lock."""

    def __init__(self, inner, san: ThreadSanitizer, name: str, reentrant: bool):
        self._inner = inner
        self._san = san
        self.sync_name = name
        self.sync_reentrant = reentrant
        self.sync_acquired_at: Optional[float] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                self._san.note_acquire(self)
            return got
        if self._inner.acquire(False):
            self._san.note_acquire(self)
            return True
        t0 = time.monotonic()
        got = self._inner.acquire(True, timeout)
        self._san.note_contention(self, (time.monotonic() - t0) * 1000.0)
        if got:
            self._san.note_acquire(self)
        return got

    def release(self) -> None:
        self._san.note_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # threading._after_fork reinitializes every lock it knows about in
        # the child; without this delegation a fork with instrumented
        # Events/Conditions alive would AttributeError inside threading
        self._inner._at_fork_reinit()
        self.sync_acquired_at = None

    # Condition protocol ------------------------------------------------------

    def _is_owned(self) -> bool:
        return self._san.owned(self)

    def _release_save(self):
        saved = self._san.drop_while_waiting(self)
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), saved)
        self._inner.release()
        return (None, saved)

    def _acquire_restore(self, state) -> None:
        inner_state, saved = state
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_state)
        else:
            self._inner.acquire()
        self._san.restore_after_wait(self, saved)

    def __repr__(self) -> str:
        return f"<sheepsync {self.sync_name} wrapping {self._inner!r}>"


# -- factories (what threading.Lock/RLock/Condition become) --------------------


def _make_lock():
    san = _STATE
    if san is None:
        return _real_lock()
    return _InstrumentedLock(_real_lock(), san, san.name_for_site(), False)


def _make_rlock():
    san = _STATE
    if san is None:
        return _real_rlock()
    return _InstrumentedLock(_real_rlock(), san, san.name_for_site(), True)


def _make_condition(lock=None):
    san = _STATE
    if san is None:
        return _real_condition(lock)
    if lock is None:
        lock = _InstrumentedLock(_real_rlock(), san, san.name_for_site(), True)
    return _real_condition(lock)


# -- lifecycle -----------------------------------------------------------------


def install(ledger: Optional[dict] = None) -> ThreadSanitizer:
    """Patch the threading factories; idempotent. Loads the committed
    concurrency ledger unless an explicit one (or {}) is passed."""
    global _STATE
    if _STATE is not None:
        return _STATE
    if ledger is None:
        from . import concurrency_check

        ledger = concurrency_check.load_ledger() or {}
    _STATE = ThreadSanitizer(ledger)
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    threading.Condition = _make_condition
    _STATE._emit(
        "sync.sanitizer_start",
        committed_edges=len(_STATE.committed),
        known_sites=len(_STATE.sites),
        pid=os.getpid(),
    )
    return _STATE


def uninstall() -> Optional[dict]:
    """Restore the real factories; returns the final summary. Locks
    already handed out stay instrumented (and keep working) — only new
    allocations revert."""
    global _STATE
    if _STATE is None:
        return None
    summary = _STATE.summary()
    _STATE._emit(
        "sync.sanitizer_stop",
        order_violations=len(summary["violations"]),
        undeclared_edges=len(summary["undeclared_edges"]),
    )
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    threading.Condition = _real_condition
    _STATE = None
    return summary


def installed() -> Optional[ThreadSanitizer]:
    return _STATE


def maybe_install_from_env() -> Optional[ThreadSanitizer]:
    if os.environ.get(ENV_VAR, "0") not in ("0", "", "false", "off"):
        return install()
    return None


def gauges() -> dict[str, float]:
    """Telemetry gauge hook: {} when the sanitizer is not installed."""
    return _STATE.gauges() if _STATE is not None else {}
