"""sheepmem: static memory & buffer-lifetime analysis over the compiled plan.

The ledger family audits compute (sheepcheck, jaxpr_check.py) and
collectives (sheepshard, shard_check.py) but was blind to the resource that
actually caps a TPU run: device memory. MSRL (arXiv:2210.00882) and
MindSpeed RL (arXiv:2507.19017) treat per-fragment memory footprints as
first-class placement inputs — the replay service, serving tier, and
fragment graph on the ROADMAP all need to know, per jit, "how many bytes
does one dispatch of you hold live?" before anything can be placed or
admission-controlled. This module closes that gap: every registered jit of
every capture spec (the 13 mains, the `@bf16`/Anakin CAPTURE_VARIANTS, and
the mesh-bearing SHARD_SWEEP configurations) is lowered AND compiled (CPU
virtual mesh, zero execution) and two sources are read off the executable:

  - XLA's own `memory_analysis()` (CompiledMemoryStats): argument / output
    / temp / generated-code bytes, summed into the peak the runtime must
    provision (`peak = args + outputs + temps + code`; the alias counter is
    skipped — XLA only reports it on fresh compiles, so netting it out
    would drift with persistent-cache state);
  - the post-optimization HLO text: the realized `input_output_alias`
    table (which DECLARED donations XLA actually honored), every
    executable-embedded array constant, and each `while` loop's carried
    buffers with `known_trip_count` — the buffers that stay live across
    every iteration of the dreamer imagination/RSSM scans, i.e. the remat
    advisor's input.

Rule catalog (continues the SC numbering; suppressions in
`MEM_SUPPRESSIONS`, keyed `(spec, jit, rule)`, justification mandatory):

  SC010  missed donation — an undonated input whose (shape, dtype) byte-
         matches an output, above a size floor: the caller's buffer could
         be reused in place, instead the dispatch holds both copies live.
  SC011  dropped donation — an argument DECLARED donated whose param index
         never appears in the executable's realized input_output_alias
         table: XLA silently refused the alias, so the jit's peak holds
         donor and output simultaneously (silent peak doubling). Checked
         against the compiled module, not the jaxpr — sheepcheck SC003 is
         the jaxpr-level screen, this is the receipt.
  SC012  large closure-captured constant baked into the executable — a
         big array literal in the optimized HLO bloats every persistent-
         cache entry, is re-materialized per executable, and can never be
         donated or sharded. sheeplint SL011 is the source-level twin.
  SC013  per-shard peak over budget — a mesh-bearing jit whose per-
         participant peak exceeds the configured HBM budget: the config
         would OOM on a real chip of that size regardless of schedule.

Fingerprints are committed as the `memory` section of the per-spec
`analysis/budget/` files; `tools/sheepmem.py --check-budget` is the CI
drift gate: peak growth past tolerance, lost realized aliases, new large
constants, per-shard budget breaches, and any `@bf16` variant whose
full-width activation bytes are not measurably below its f32 twin
(`wide_activation_bytes` — the byte-level receipt of the ISSUE-9 mixed-
precision contract) all fail; reductions are notes.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, Iterable, Iterator

from .rules import Rule
from . import jaxpr_check as jc
from . import shard_check as sc

__all__ = [
    "MEM_RULES",
    "MEM_SUPPRESSIONS",
    "MEM_VARIANTS",
    "MemReport",
    "analyze_entry",
    "analyze_mem_plan",
    "build_memory_budget",
    "check_memory_budget",
    "constant_floor",
    "donation_floor",
    "memory_fingerprint",
    "memory_sweep_specs",
    "parse_embedded_constants",
    "parse_io_aliases",
    "parse_scan_buffers",
    "peak_budget_bytes",
    "remat_advice",
    "resolve_capture",
]

ERROR = "error"
WARNING = "warning"

_MEM_RULES = [
    Rule(
        id="SC010",
        name="missed-donation",
        severity=WARNING,
        summary=(
            "undonated input whose (shape, dtype) byte-matches an output "
            "above the size floor — the caller's buffer could be reused in "
            "place, instead the dispatch holds input and output copies "
            "live simultaneously"
        ),
        autofix=(
            "donate the argument (donating_jit / donate_argnums) when the "
            "caller discards it after the call; suppress with the "
            "justification where the caller genuinely re-reads the buffer"
        ),
    ),
    Rule(
        id="SC011",
        name="dropped-donation",
        severity=WARNING,
        summary=(
            "argument declared donated but ABSENT from the executable's "
            "realized input_output_alias table — XLA silently refused the "
            "alias, so the jit's peak holds donor and output buffers "
            "simultaneously (silent peak doubling)"
        ),
        autofix=(
            "make the donated argument's aval exactly match a returned "
            "output (same shape, dtype, and sharding) so XLA can realize "
            "the alias, or drop the donation"
        ),
    ),
    Rule(
        id="SC012",
        name="embedded-constant",
        severity=WARNING,
        summary=(
            "large array constant baked into the compiled executable "
            "(a closure-captured module-level ndarray, a materialized "
            "table) — bloats every persistent-cache entry, re-materializes "
            "per executable, and can never be donated or sharded"
        ),
        autofix=(
            "pass the array as a jit argument (it becomes a device buffer "
            "shared across executables), or construct it inside the jit "
            "from an iota/broadcast; sheeplint SL011 catches the closure "
            "pattern at source level"
        ),
    ),
    Rule(
        id="SC013",
        name="per-shard-peak-over-budget",
        severity=ERROR,
        summary=(
            "mesh-bearing jit whose per-participant peak bytes exceed the "
            "configured HBM budget (SHEEPRL_TPU_MEM_PEAK_BUDGET_MB) — the "
            "sharded config would OOM on a chip of that size regardless "
            "of schedule"
        ),
        autofix=(
            "shard the offending operands over more axes, chunk the batch "
            "(decide_batch_chunk), or remat the scan bodies the peak "
            "report names"
        ),
    ),
]

MEM_RULES: dict[str, Rule] = {r.id: r for r in _MEM_RULES}

# (spec, jit, rule) -> justification; same auditable contract as
# jaxpr_check.SUPPRESSIONS and shard_check.SHARD_SUPPRESSIONS.
MEM_SUPPRESSIONS: dict[tuple[str, str, str], str] = {
    # The recurrent player carries its LSTM state through the policy step:
    # (h, c) in -> (h, c) out every env step. The caller (the collection
    # loop) immediately overwrites its reference, so donation WOULD be
    # legal — but the same buffers also feed the stored trajectory, and at
    # 8-unit capture widths the pair is <2KiB; the floor only trips here
    # because the obs history window byte-matches. Revisit with ROADMAP-2's
    # replay service, which owns those buffers explicitly.
    ("ppo_recurrent", "policy_step", "SC010"): (
        "LSTM carry is also referenced by the stored trajectory; donation "
        "would invalidate the replay view"
    ),
    ("ppo_recurrent@bf16", "policy_step", "SC010"): (
        "LSTM carry is also referenced by the stored trajectory; donation "
        "would invalidate the replay view"
    ),
}

# ---------------------------------------------------------------------------
# floors / budgets (env-tunable, mirroring shard_check's replicated floor)
# ---------------------------------------------------------------------------


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def donation_floor() -> int:
    """SC010 fires only for buffers at least this large. The default
    (512 B) is sized to the TINY capture avals the committed sweep runs at
    — an LSTM carry at capture width is ~512 B but scales with
    envs x hidden at live widths, so the capture-scale finding is the real
    one. Raise via env for production-scale one-off audits."""
    return _env_int("SHEEPRL_TPU_MEM_DONATION_FLOOR", 512)


def alias_floor() -> int:
    """SC011 ignores dropped donations smaller than this (default 1 KiB —
    a refused scalar alias costs nothing)."""
    return _env_int("SHEEPRL_TPU_MEM_ALIAS_FLOOR", 1 << 10)


def constant_floor() -> int:
    """SC012 fires for embedded constants at least this large (default
    16 KiB per constant)."""
    return _env_int("SHEEPRL_TPU_MEM_CONSTANT_FLOOR", 1 << 14)


def peak_budget_bytes() -> int:
    """SC013 per-shard peak budget (default 512 MiB — far above any tiny-
    width capture, sized so a pathological sharded config still trips)."""
    return _env_int("SHEEPRL_TPU_MEM_PEAK_BUDGET_MB", 512) * (1 << 20)


# ---------------------------------------------------------------------------
# HLO text parsing: realized aliases, embedded constants, scan buffers
# ---------------------------------------------------------------------------

# `input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, must-alias) }`
# on the HloModule header line; inner braces force the non-greedy nested
# scan below.
_ALIAS_TABLE_RE = re.compile(
    r"input_output_alias=\{((?:\{[^{}]*\}|[^{}])*)\}"
)
_ALIAS_PAIR_RE = re.compile(r"\{([0-9,\s]*)\}:\s*\((\d+),\s*\{[0-9,\s]*\}")

# `%constant.3 = f32[64,64]{1,0} constant(...)` — the result type token
# carries the full shape; the literal itself may be elided (`{...}`).
_CONST_RE = re.compile(
    r"=\s*([a-z0-9]+\[[0-9,]*\])(?:\{[^}]*\})?\s+constant\("
)

# `%w = (s32[], f32[4,16]{1,0}) while((...) %t), condition=..., body=...`
_WHILE_RE = re.compile(r"=\s*(\([^=]*?\)|\S+)\s+while\(")


def parse_io_aliases(hlo_text: str) -> list[str]:
    """The realized input->output aliases of a compiled module, as stable
    `out{<output index>}<-arg<param>` strings (what the ledger commits and
    the SC011/lost-alias gates compare)."""
    header = hlo_text.split("\n", 1)[0]
    m = _ALIAS_TABLE_RE.search(header)
    if m is None:
        return []
    out = []
    for out_idx, param in _ALIAS_PAIR_RE.findall(m.group(1)):
        out.append(f"out{{{out_idx.replace(' ', '')}}}<-arg{param}")
    return sorted(out)


def aliased_params(aliases: Iterable[str]) -> set[int]:
    """Param indexes that realized at least one alias."""
    out: set[int] = set()
    for a in aliases:
        m = re.search(r"<-arg(\d+)$", a)
        if m:
            out.add(int(m.group(1)))
    return out


def parse_embedded_constants(hlo_text: str) -> list[tuple[int, str]]:
    """Every array constant instruction of the optimized module as
    `(bytes, "f32[64,64]")`, largest first. Scalars are included (they
    cost almost nothing and the SC012 floor screens them)."""
    out: list[tuple[int, str]] = []
    for token in _CONST_RE.findall(hlo_text):
        out.append((sc._shape_bytes(token), token))
    out.sort(key=lambda t: (-t[0], t[1]))
    return out


def parse_scan_buffers(hlo_text: str) -> list[dict]:
    """Per `while` loop of the optimized module: the carried buffers that
    stay live across EVERY iteration, with the loop's `known_trip_count`
    when XLA printed one. Returns one record per carried buffer (largest
    first): `{"shape", "bytes", "trip_count"}` — the remat advisor's raw
    material for the dreamer imagination/RSSM scans."""
    records: list[dict] = []
    for line in hlo_text.splitlines():
        m = _WHILE_RE.search(line)
        if m is None:
            continue
        trip_m = sc._TRIP_RE.search(line)
        trip = int(trip_m.group(1)) if trip_m else None
        for dtype, dims in sc._SHAPE_RE.findall(m.group(1)):
            n = 1
            for d in dims.split(","):
                if d.strip():
                    n *= int(d)
            nbytes = n * sc._DTYPE_BYTES.get(dtype, 4)
            shape = f"{dtype}[{dims}]"
            records.append(
                {"shape": shape, "bytes": nbytes, "trip_count": trip}
            )
    records.sort(key=lambda r: (-r["bytes"], r["shape"]))
    return records


# ---------------------------------------------------------------------------
# the memory fingerprint
# ---------------------------------------------------------------------------


def _activation_bytes(closed: Any) -> tuple[int, int]:
    """`(total, wide)` bytes over every intermediate (eqn output) aval of
    the traced program, recursively through scan/cond bodies. `wide` counts
    only float32/float64 leaves — under the ISSUE-9 bf16 policy the compute
    moves to half width, so a `@bf16` jit's wide bytes MUST undercut its
    f32 twin even though cast buffers grow the total. That strict
    inequality is the byte-level receipt `check_memory_budget` enforces."""
    total = wide = 0
    for eqn in jc.iter_eqns(closed):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            dtype = getattr(aval, "dtype", None)
            if shape is None or dtype is None:
                continue
            n = 1
            for d in shape:
                n *= int(d)
            nbytes = n * int(getattr(dtype, "itemsize", 4))
            total += nbytes
            if getattr(dtype, "name", "") in ("float32", "float64"):
                wide += nbytes
    return total, wide


_SCAN_BUFFERS_KEPT = 4


def memory_fingerprint(compiled: Any, closed: Any, donated: list[bool]) -> dict:
    """The committed per-jit memory fingerprint: CompiledMemoryStats
    counters, realized aliases, embedded constants, live-across-scan
    buffers, and the jaxpr-level activation footprint."""
    ma = compiled.memory_analysis()
    arg = int(getattr(ma, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(ma, "output_size_in_bytes", 0) or 0)
    temp = int(getattr(ma, "temp_size_in_bytes", 0) or 0)
    gen = int(getattr(ma, "generated_code_size_in_bytes", 0) or 0)
    text = compiled.as_text()
    aliases = parse_io_aliases(text)
    constants = parse_embedded_constants(text)
    floor = constant_floor()
    total_act, wide_act = _activation_bytes(closed)
    header = text.split("\n", 1)[0]
    m = re.search(r"num_partitions=(\d+)", header)
    dtypes = sorted(
        {
            getattr(getattr(a, "dtype", None), "name", "")
            for a in jc._all_avals(closed)
        }
        - {""}
    )
    return {
        # the bytes one dispatch must have provisioned. Deliberately does
        # NOT subtract CompiledMemoryStats.alias_size_in_bytes: XLA reports
        # it only on FRESH compiles (a persistent-cache deserialization
        # returns 0), so a peak that nets it out drifts with cache state —
        # the realized aliasing lives in the stable `aliases` table instead
        "peak_bytes": arg + out + temp + gen,
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "generated_code_bytes": gen,
        "aliases": aliases,
        "donated": int(sum(donated)),
        "constant_bytes": int(sum(b for b, _ in constants)),
        "large_constants": sorted(
            f"{shape}:{b}" for b, shape in constants if b >= floor
        ),
        "activation_bytes": total_act,
        "wide_activation_bytes": wide_act,
        "declares_bf16": "bfloat16" in dtypes,
        "declares_int8": "int8" in dtypes,
        "num_partitions": int(m.group(1)) if m else 1,
        "scan_buffers": parse_scan_buffers(text)[:_SCAN_BUFFERS_KEPT],
    }


# ---------------------------------------------------------------------------
# per-entry analysis
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class MemReport:
    spec: str
    name: str
    memory: dict | None = None  # the committed memory fingerprint
    findings: list[jc.Finding] = dataclasses.field(default_factory=list)
    error: str | None = None

    @property
    def failing(self) -> list[jc.Finding]:
        return [f for f in self.findings if not f.suppressed]


def _check_sc010(closed: Any, donated: list[bool]) -> Iterator[str]:
    """Undonated inputs whose aval byte-matches an output, above the floor.
    Outputs already claimed by a donated input (the realized or intended
    alias) are taken out of the pool first, mirroring SC003's greedy
    matching, so a properly donated train state never double-reports."""
    floor = donation_floor()
    inner = closed.jaxpr

    def key_of(var):
        aval = getattr(var, "aval", None)
        return (getattr(aval, "shape", None), getattr(aval, "dtype", None))

    pool = [key_of(v) for v in inner.outvars if hasattr(v, "aval")]
    for var, is_donated in zip(inner.invars, donated):
        if is_donated and key_of(var) in pool:
            pool.remove(key_of(var))
    for i, (var, is_donated) in enumerate(zip(inner.invars, donated)):
        if is_donated:
            continue
        nbytes = sc._aval_bytes(getattr(var, "aval", None))
        if nbytes < floor:
            continue
        key = key_of(var)
        if key in pool:
            pool.remove(key)
            yield (
                f"input {i} ({jc._aval_str(var.aval)}, {_fmt(nbytes)}) is "
                "not donated but byte-matches an output — one dispatch "
                "holds both copies live; donate it if the caller discards "
                "its reference"
            )


def _check_sc011(
    closed: Any, donated: list[bool], aliases: list[str]
) -> Iterator[str]:
    realized = aliased_params(aliases)
    floor = alias_floor()
    for i, (var, is_donated) in enumerate(zip(closed.jaxpr.invars, donated)):
        if not is_donated or i in realized:
            continue
        nbytes = sc._aval_bytes(getattr(var, "aval", None))
        if nbytes < floor:
            continue
        yield (
            f"donated arg {i} ({jc._aval_str(var.aval)}, {_fmt(nbytes)}) "
            "has NO realized input_output_alias in the executable — XLA "
            "dropped the donation, the dispatch holds donor and output "
            "simultaneously"
        )


def _check_sc012(fingerprint: dict) -> Iterator[str]:
    for item in fingerprint.get("large_constants", []):
        shape, _, nbytes = item.rpartition(":")
        yield (
            f"embedded constant {shape} ({_fmt(int(nbytes))}) baked into "
            "the executable — bloats every cache entry and can never be "
            "donated or sharded; pass it as an argument instead"
        )


def _check_sc013(fingerprint: dict) -> Iterator[str]:
    if fingerprint.get("num_partitions", 1) <= 1:
        return
    budget = peak_budget_bytes()
    peak = int(fingerprint.get("peak_bytes", 0))
    if peak > budget:
        yield (
            f"per-shard peak {_fmt(peak)} exceeds the "
            f"{_fmt(budget)} HBM budget on the "
            f"{fingerprint['num_partitions']}-device mesh — this config "
            "OOMs on a chip of that size regardless of schedule"
        )


def _fmt(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def analyze_entry(
    spec: str,
    entry: Any,
    rules: set[str] | None = None,
) -> MemReport:
    """Lower-and-compile one CompilePlan entry and extract its memory
    fingerprint + SC010-SC013 findings. Unlike sheepshard, every entry is
    analyzable — single-device jits have peaks too."""
    from ..compile.plan import avals_of

    report = MemReport(spec=spec, name=entry.name)
    fn, example = entry.fn, entry.example
    if example is None:
        report.error = "no example thunk (registered for timing only)"
        return report
    if not hasattr(fn, "trace") or not hasattr(fn, "lower"):
        report.error = "not traceable (wrapped callable without .trace/.lower)"
        return report
    try:
        specs = avals_of(example())
        traced = fn.trace(*specs)
        closed = traced.jaxpr
        lowered = traced.lower()
        compiled = lowered.compile()
    except Exception as err:
        report.error = f"lower/compile failed: {type(err).__name__}: {err}"[:300]
        return report
    donated = jc._donated_flags(lowered, closed)
    report.memory = memory_fingerprint(compiled, closed, donated)

    def emit(rule_id: str, messages: Iterable[str]) -> None:
        if rules is not None and rule_id not in rules:
            return
        for message in messages:
            finding = jc.Finding(MEM_RULES[rule_id], spec, entry.name, message)
            finding.suppressed = MEM_SUPPRESSIONS.get((spec, entry.name, rule_id))
            report.findings.append(finding)

    emit("SC010", _check_sc010(closed, donated))
    emit("SC011", _check_sc011(closed, donated, report.memory["aliases"]))
    emit("SC012", _check_sc012(report.memory))
    emit("SC013", _check_sc013(report.memory))
    return report


def analyze_mem_plan(
    spec: str, plan: Any, rules: set[str] | None = None
) -> list[MemReport]:
    entries = plan._entries
    if spec in MEM_VARIANTS:
        # the remat-receipt twins exist for ONE comparison: the train
        # step's peak with and without remat. The other registered jits
        # are identical to the base spec's programs at inflated shapes —
        # fingerprinting them would only churn the ledger.
        entries = [e for e in entries if e.name == "train_step"]
    return [analyze_entry(spec, entry, rules=rules) for entry in entries]


# ---------------------------------------------------------------------------
# the sweep: every capture population the other ledgers use, unified
# ---------------------------------------------------------------------------


# Memory-only capture variants (ISSUE 11): the remat receipt twins. Both
# run dreamer_v1 at SCAN-DOMINANT shapes — pixel obs so the conv
# encoder/decoder carries the exec time while the RSSM/imagination scan
# backward carries the peak (T=64 x B=16 rows live across both scans) —
# once plain and once under `--remat on`. The capture drops `--dry_run`
# (its T<=2 sequence clamp would collapse the scans; capture raises at
# plan.start() before anything executes, so the full shapes are safe) and
# `check_memory_budget` gates the pair: the @remat train step's peak must
# undercut its @scan twin by `remat_peak_frac` (default 20%) — the
# CI-ledgered receipt that the remat plumbing keeps buying its bytes.
_SCAN_HEAVY = [
    "--no_dry_run",
    "--per_rank_sequence_length", "64",
    "--per_rank_batch_size", "16",
    "--recurrent_state_size", "256",
    "--hidden_size", "256",
    "--stochastic_size", "64",
    "--horizon", "15",
    "--dense_units", "64",
    "--cnn_channels_multiplier", "4",
    "--buffer_size", "128",
    # with the continue predictor off, the imagination discount triangle
    # is cumprod(ones*gamma) — XLA folds it into [H-1, T*B, 1] constants
    # that trip SC012 at these shapes; with it on, the discount depends on
    # data and the twins stay finding-free
    "--use_continues",
]

MEM_VARIANTS: dict[str, tuple[str, list[str]]] = {
    "dreamer_v1@scan": ("dreamer_v1", list(_SCAN_HEAVY)),
    "dreamer_v1@remat": ("dreamer_v1", [*_SCAN_HEAVY, "--remat", "on"]),
}


def memory_sweep_specs() -> list[str]:
    """The full memory-sweep population: all registered mains at their
    CAPTURE_ARGV, every CAPTURE_VARIANT (`@bf16`, Anakin), every
    mesh-bearing SHARD_SWEEP spec, and the memory-only MEM_VARIANTS
    (the `@scan`/`@remat` remat-receipt twins). Where a spec name appears
    in both (ppo@anakin, dreamer_v3@anakin) the SHARD_SWEEP mesh argv
    wins — the per-shard peak is the TPU-relevant quantity (SC013)."""
    import sheeprl_tpu.algos  # noqa: F401 — fire registrations
    from sheeprl_tpu.utils.registry import tasks

    specs = [*sorted(tasks), *sorted(jc.CAPTURE_VARIANTS)]
    specs += [s for s in sorted(sc.SHARD_SWEEP) if s not in specs]
    specs += [s for s in sorted(MEM_VARIANTS) if s not in specs]
    return specs


def resolve_capture(spec: str) -> tuple[str, list[str]]:
    """Capture argv for a memory-sweep spec: MEM_VARIANTS first (memory-
    only twins), then SHARD_SWEEP (mesh overrides), then CAPTURE_VARIANTS,
    then the plain algo."""
    if spec in MEM_VARIANTS:
        return MEM_VARIANTS[spec]
    return sc.resolve_capture(spec)


# ---------------------------------------------------------------------------
# remat advisor
# ---------------------------------------------------------------------------


def remat_advice(memory: dict[str, dict], top: int = 8) -> list[str]:
    """Rank every live-across-scan buffer of a memory section by bytes and
    render the top candidates: the buffers `jax.checkpoint` on the scan
    body would stop keeping live for the whole trip count (the dreamer
    imagination/RSSM scans are the intended audience)."""
    rows: list[tuple[int, str]] = []
    for key, fp in memory.items():
        for buf in fp.get("scan_buffers", []):
            trip = buf.get("trip_count")
            trip_s = f"x{trip} known iterations" if trip else "unknown trip count"
            rows.append(
                (
                    int(buf["bytes"]),
                    f"{key}: {buf['shape']} ({_fmt(int(buf['bytes']))}) live "
                    f"across a while/scan body ({trip_s}) — a remat "
                    "(jax.checkpoint) candidate if the peak report names "
                    "this jit",
                )
            )
    rows.sort(key=lambda r: (-r[0], r[1]))
    return [msg for _, msg in rows[:top]]


# ---------------------------------------------------------------------------
# memory ledger: build + drift gate
# ---------------------------------------------------------------------------


def build_memory_budget(
    reports: list[MemReport],
    peak_bytes_frac: float = 0.25,
    remat_peak_frac: float = 0.20,
) -> dict:
    import jax

    return {
        "version": 1,
        "jax_version": jax.__version__,
        "tolerance": {
            "peak_bytes_frac": peak_bytes_frac,
            "remat_peak_frac": remat_peak_frac,
        },
        "memory": {
            f"{r.spec}/{r.name}": r.memory
            for r in reports
            if r.memory is not None
        },
    }


def _bf16_twin(key: str) -> str | None:
    spec, _, jit = key.partition("/")
    if not spec.endswith("@bf16"):
        return None
    return f"{spec[: -len('@bf16')]}/{jit}"


def _int8_twin(key: str) -> str | None:
    """`X@int8/policy_b2` -> `X/policy_b2` (the quantized serving twin's
    byte receipt pairs each rung against the same rung captured at the
    checkpoint dtype)."""
    spec, _, jit = key.partition("/")
    if not spec.endswith("@int8"):
        return None
    return f"{spec[: -len('@int8')]}/{jit}"


def _remat_twin(key: str) -> str | None:
    """`X@remat/train_step` -> `X@scan/train_step` (the remat receipt only
    gates the train step — the other jits of the twin captures are
    identical programs and would trivially fail a reduction gate)."""
    spec, _, jit = key.partition("/")
    if not spec.endswith("@remat") or jit != "train_step":
        return None
    return f"{spec[: -len('@remat')]}@scan/{jit}"


def check_memory_budget(ledger: dict, derived: dict) -> tuple[list[str], list[str]]:
    """The CI memory drift gate. Failures are the ISSUE-gated classes:
    added/removed entries, peak growth past tolerance, lost realized
    aliases, new large constants, per-shard peaks over the HBM budget, and
    a `@bf16` variant whose full-width activation bytes do not undercut
    its f32 twin. Reductions and new aliases are notes."""
    failures: list[str] = []
    notes: list[str] = []
    tol = float(ledger.get("tolerance", {}).get("peak_bytes_frac", 0.25))
    old, new = ledger.get("memory", {}), derived.get("memory", {})
    for key in sorted(set(old) - set(new)):
        failures.append(f"{key}: memory fingerprint disappeared (ledger has it)")
    for key in sorted(set(new) - set(old)):
        failures.append(f"{key}: new jit not in the memory ledger")
    for key in sorted(set(old) & set(new)):
        o, n = old[key], new[key]
        op, np_ = int(o.get("peak_bytes", 0)), int(n.get("peak_bytes", 0))
        if np_ > op * (1.0 + tol) and np_ - op > 4096:
            failures.append(
                f"{key}: peak bytes grew {op} -> {np_} "
                f"(+{(np_ - op) / max(op, 1):.0%}, tolerance {tol:.0%})"
            )
        elif np_ < op * (1.0 - tol) and op - np_ > 4096:
            notes.append(
                f"{key}: peak bytes shrank {op} -> {np_} — refresh the ledger"
            )
        lost = sorted(set(o.get("aliases", [])) - set(n.get("aliases", [])))
        if lost:
            failures.append(
                f"{key}: realized alias(es) lost {lost} — a donation XLA "
                "used to honor is gone (silent peak doubling)"
            )
        gained = sorted(set(n.get("aliases", [])) - set(o.get("aliases", [])))
        if gained:
            notes.append(f"{key}: new realized alias(es) {gained}")
        new_consts = sorted(
            set(n.get("large_constants", [])) - set(o.get("large_constants", []))
        )
        if new_consts:
            failures.append(
                f"{key}: new large embedded constant(s) {new_consts} — "
                "baked into every cache entry (SC012)"
            )
        dropped = sorted(
            set(o.get("large_constants", [])) - set(n.get("large_constants", []))
        )
        if dropped:
            notes.append(f"{key}: embedded constant(s) eliminated {dropped}")
    budget = peak_budget_bytes()
    for key in sorted(new):
        n = new[key]
        if int(n.get("num_partitions", 1)) > 1 and int(n.get("peak_bytes", 0)) > budget:
            failures.append(
                f"{key}: per-shard peak {n['peak_bytes']} exceeds the "
                f"{budget}-byte HBM budget on the "
                f"{n['num_partitions']}-device mesh"
            )
    # the bf16 byte receipt: a declared-bf16 jit must move enough compute
    # to half width that its full-width intermediate footprint undercuts
    # the f32 twin — strictly, at any capture scale
    for key in sorted(new):
        twin = _bf16_twin(key)
        if twin is None or twin not in new:
            continue
        if not new[key].get("declares_bf16"):
            continue
        bw = int(new[key].get("wide_activation_bytes", 0))
        fw = int(new[twin].get("wide_activation_bytes", 0))
        if bw >= fw:
            failures.append(
                f"{key}: full-width activation bytes {bw} not below the "
                f"f32 twin's {fw} ({twin}) — the bf16 policy is not "
                "actually narrowing the activations"
            )
        else:
            notes.append(
                f"{key}: wide activation bytes {bw} vs f32 twin {fw} "
                f"(-{(fw - bw) / max(fw, 1):.0%})"
            )
    # the int8 byte receipt (ISSUE 20): a declared-int8 serving rung must
    # actually carry quantized weights — its argument bytes must be
    # STRICTLY below the full-width twin's (int8 weights are 4x narrower
    # than f32; a rung whose arguments match the twin is serving
    # full-width params under the int8 flag)
    for key in sorted(new):
        twin = _int8_twin(key)
        if twin is None or twin not in new:
            continue
        if not new[key].get("declares_int8"):
            continue
        qa = int(new[key].get("argument_bytes", 0))
        fa = int(new[twin].get("argument_bytes", 0))
        if qa >= fa:
            failures.append(
                f"{key}: argument bytes {qa} not below the full-width "
                f"twin's {fa} ({twin}) — the int8 rung is not actually "
                "carrying quantized weights"
            )
        else:
            notes.append(
                f"{key}: argument bytes {qa} vs full-width twin {fa} "
                f"(-{(fa - qa) / max(fa, 1):.0%})"
            )
    # the remat byte receipt (ISSUE 11): the @remat twin's train step must
    # undercut its @scan twin's peak by at least `remat_peak_frac` — the
    # accepted auto-remat's ledgered reduction, re-verified on every sweep
    remat_frac = float(ledger.get("tolerance", {}).get("remat_peak_frac", 0.20))
    for key in sorted(new):
        twin = _remat_twin(key)
        if twin is None or twin not in new:
            continue
        rp = int(new[key].get("peak_bytes", 0))
        sp = int(new[twin].get("peak_bytes", 0))
        if rp > sp * (1.0 - remat_frac):
            failures.append(
                f"{key}: remat peak {rp} is not {remat_frac:.0%} below the "
                f"non-remat twin's {sp} ({twin}) — the remat plumbing "
                "stopped buying its bytes"
            )
        else:
            notes.append(
                f"{key}: remat peak {rp} vs non-remat twin {sp} "
                f"(-{(sp - rp) / max(sp, 1):.0%})"
            )
    return failures, notes
