"""Static analysis (sheeplint) + runtime sanitizer for JAX/TPU hazards.

Two halves of one invariant set (ISSUE 3):

  - `linter` / `rules`: AST-level detection of hazards that are knowable
    before running anything — bare donating jits (SL001), host syncs inside
    traced bodies (SL002), Python branches on tracers (SL003), per-step
    recompile patterns (SL004), unregistered dataclass pytrees (SL005),
    unconstrained sharded jits (SL006). CLI: `python tools/sheeplint.py`.
  - `sanitizer`: the runtime half for what the AST cannot see — a
    transfer-guard wrapper that catches *actual* implicit host<->device
    transfers in guarded phases, and checkify NaN/div instrumentation on
    train steps — enabled per-run with `--sanitize`, reporting through the
    telemetry JSONL event log.
  - `jaxpr_check`: the IR half (ISSUE 7, `tools/sheepcheck.py`) — every
    hot jit registered in a main's CompilePlan is abstract-evaled to a
    ClosedJaxpr (shape capture, zero execution) and analyzed for hazards
    the AST cannot see through the jit boundary (SC001-SC005: dtype
    promotion, host callbacks, donation aliasing, scan-carry weak types,
    CPU conv pathology), plus the compile-cost fingerprints behind the
    CI-gated `analysis/budget/` ledger.
  - `shard_check`: the SPMD half (ISSUE 8, `tools/sheepshard.py`) — every
    mesh-bearing registered jit is lowered AND compiled under its declared
    mesh (still zero execution) and the partitioned HLO is analyzed for
    communication hazards the jaxpr cannot show (SC006-SC009: hot-loop
    collectives, silent full replication, cross-jit resharding thrash on
    declared data edges, eager host-loop collectives), plus the per-jit
    comms ledger behind the CI-gated comms drift budget.
  - `memory_check`: the device-memory half (ISSUE 10, `tools/sheepmem.py`)
    — every registered jit is compiled and its memory fingerprint read off
    XLA's `memory_analysis()` + the optimized HLO (SC010-SC013: missed and
    dropped donations, executable-embedded constants, per-shard peaks over
    budget), plus the `memory` ledger section behind the CI-gated HBM
    drift budget and the bf16 activation-byte receipt.
  - `concurrency_check`: the host-side half (ISSUE 18,
    `tools/sheepsync.py`) — an AST pass over the threaded runtime tiers
    (flock/serve/telemetry/resilience/parallel/compile) builds the
    per-module lock graph, thread inventory and FLK1 send/recv contexts,
    and checks SY001-SY006 (lock-order cycles, blocking calls under a
    held lock, unguarded shared writes, manual acquire without
    try/finally, Condition.wait outside a predicate loop, protocol
    sequencing), plus the `concurrency` ledger behind the CI-gated
    lock-graph drift budget.
  - `thread_sanitizer`: concurrency_check's runtime half — instrumented
    Lock/RLock/Condition factories record per-thread acquisition order
    and assert it against the committed lock-order DAG
    (`--sanitize_threads` / SHEEPRL_TPU_SANITIZE_THREADS=1), emitting
    `sync.order_violation` events and `Sync/*` gauges.
"""

from . import concurrency_check, jaxpr_check, memory_check, shard_check, thread_sanitizer
from .linter import lint_file, lint_paths, lint_source
from .rules import RULES, Rule, Violation
from .sanitizer import Sanitizer

__all__ = [
    "RULES",
    "concurrency_check",
    "jaxpr_check",
    "memory_check",
    "shard_check",
    "thread_sanitizer",
    "Rule",
    "Violation",
    "Sanitizer",
    "lint_file",
    "lint_paths",
    "lint_source",
]
