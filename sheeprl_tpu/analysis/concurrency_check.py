"""sheepsync — static concurrency & wire-protocol analysis (ISSUE 18).

The four older gates (sheeplint/sheepcheck/sheepshard/sheepmem) analyze
jitted/XLA code; this module covers the other half of the runtime: the
threaded host Python behind flock's replay service, serve's
batcher/server/hot-reload slot, the tracer and the fault-recovery paths.
One AST pass over `sheeprl_tpu/{flock,serve,telemetry,resilience,
parallel,compile}` builds

  - a **lock graph**: every Lock/RLock/Condition allocation gets a stable
    identity (`flock.service.ReplayService._lock`; a dict-of-locks
    comprehension collapses to `..._shard_locks[*]`; a Condition built on
    a shared lock is acquired AS that lock), every `with` site is
    attributed to its function, and nested acquisitions — including
    through same-class / same-package calls made while a lock is held —
    become directed edges `outer -> inner`;
  - a **thread inventory**: every `threading.Thread`/`Timer` construction
    (and Thread subclass) with target, name template, daemon flag and
    best-effort join evidence;
  - a **guard map**: for each class attribute written outside `__init__`
    from more than one thread entry point, the lock (if any) that
    dominates *every* write.

and checks six rules over them:

  SY001  lock-order cycle across the acquisition graph (potential
         deadlock; both chains reported). Nested re-acquisition of a
         plain (non-reentrant) Lock is a self-deadlock and also fires;
         RLock/Condition self-nesting is reentrant and exempt, as are
         `[*]` dict-lock pairs (index unknown statically).
  SY002  blocking call under a held lock: socket send/recv/accept/
         connect (incl. the `wire.*` frame helpers), `Thread.join`,
         `Event.wait`, `time.sleep`, checkpoint-restore / `*loader*`
         calls, `subprocess.*` — directly or through a call made with
         the lock held. `Condition.wait` is exempt (it releases its
         backing lock).
  SY003  shared mutable attribute written from >= 2 thread entry points
         (thread targets / Thread-subclass `run` / the public-API root)
         without one common dominating lock.
  SY004  manual `.acquire()` whose `.release()` is not in a `finally:`
         of the same function (prefer `with`).
  SY005  `Condition.wait` outside an enclosing loop that re-checks the
         predicate (`wait_for` is exempt: the predicate is the argument).
  SY006  FLK1 protocol sequencing, from the pinned `flock/wire.py`
         registry: a freshly `wire.connect`-ed socket whose first send
         is not HELLO/PROFILE, or a reply kind (WELCOME/PUSH_OK/
         HEARTBEAT_OK/WEIGHTS/WEIGHTS_UNCHANGED/ERROR/RESPONSE/SHED)
         sent from a function not reachable from a frame-receiving
         handler.

Findings are suppressed only through `SYNC_SUPPRESSIONS`, keyed
`(relpath, qualname, rule)` with a mandatory justification — the same
contract as sheepmem's `MEM_SUPPRESSIONS`.

The committed ledger `analysis/budget/concurrency.json` (built by
`tools/sheepsync.py --update-budget`) records the lock-graph fingerprint,
per-role lock tables, thread inventory and guard maps; `--check-budget`
fails CI on any new lock-order edge, any cycle, a newly unguarded shared
write, or a new undeclared thread. The runtime half
(`analysis/thread_sanitizer.py`) asserts the committed edge DAG against
real per-thread acquisition order.
"""

from __future__ import annotations

import ast
import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Optional

from .linter import iter_python_files
from .rules import Rule

__all__ = [
    "SY_RULES",
    "SYNC_SUPPRESSIONS",
    "DEFAULT_PACKAGES",
    "Finding",
    "ConcurrencyReport",
    "analyze_paths",
    "analyze_source",
    "build_ledger",
    "check_budget",
    "default_paths",
    "ledger_path",
    "load_ledger",
    "render_report",
    "save_ledger",
]

ERROR = "error"

_SY_RULES = [
    Rule(
        id="SY001",
        name="lock-order-cycle",
        severity=ERROR,
        summary="Lock acquisition order forms a cycle (potential deadlock); "
        "both acquisition chains are reported. Break the cycle or collapse "
        "the locks. Nested re-acquisition of a plain Lock is the "
        "single-lock case of the same deadlock.",
        autofix=(
            "impose one global acquisition order (document it in the ledger); or collapse the two locks into one; for the single-lock case use an RLock or move the call outside the with block"
        ),
    ),
    Rule(
        id="SY002",
        name="blocking-under-lock",
        severity=ERROR,
        summary="Blocking call (socket I/O, Thread.join, Event.wait, "
        "time.sleep, checkpoint restore, subprocess) while holding a lock: "
        "every thread contending on the lock stalls behind the I/O.",
        autofix=(
            "copy the shared state out under the lock, release, then do the I/O on the local copy (the service/server send paths are the repo's reference idiom)"
        ),
    ),
    Rule(
        id="SY003",
        name="unguarded-shared-write",
        severity=ERROR,
        summary="Attribute written from >= 2 thread entry points without one "
        "common dominating lock: a data race the GIL schedules but does not "
        "prevent.",
        autofix=(
            "take the owning object's lock around every write, or funnel all writes through one thread; then rerun --update-budget so the guard map records the invariant"
        ),
    ),
    Rule(
        id="SY004",
        name="acquire-without-finally",
        severity=ERROR,
        summary="Manual .acquire() whose .release() is not in a finally of "
        "the same function: an exception between them leaks the lock "
        "forever. Use `with`.",
        autofix=(
            "replace acquire()/release() with `with lock:`; if the manual form is unavoidable, release in a finally block"
        ),
    ),
    Rule(
        id="SY005",
        name="wait-without-predicate-loop",
        severity=ERROR,
        summary="Condition.wait outside a loop that re-checks the predicate: "
        "spurious wakeups and timeout returns are indistinguishable from "
        "the real signal.",
        autofix=(
            "wrap the wait in `while not <predicate>:` (or use Condition.wait_for, which loops internally)"
        ),
    ),
    Rule(
        id="SY006",
        name="protocol-sequencing",
        severity=ERROR,
        summary="FLK1 frame sent out of protocol order: request before "
        "HELLO/PROFILE on a fresh connection, or a reply kind sent outside "
        "a request handler.",
        autofix=(
            "send HELLO (or PROFILE) first on every fresh wire.connect socket; emit reply kinds only from the conn-handler call path"
        ),
    ),
]

SY_RULES: dict[str, Rule] = {r.id: r for r in _SY_RULES}

# (relpath, qualname, rule) -> mandatory justification. `*` matches any
# qualname in the file. An unjustified suppression is a review error.
SYNC_SUPPRESSIONS: dict[tuple[str, str, str], str] = {
    ("sheeprl_tpu/flock/relay.py", "Relay._up_request", "SY002"): (
        "by design: _up_lock serializes the ONE multiplexed upstream "
        "connection (strict request/reply framing — interleaved senders "
        "would corrupt the stream). It is never taken on the downstream "
        "accept path; a stalled upstream blocks only the forwarder and "
        "heartbeat forwards, and downstream PUSHes are answered from the "
        "cached aggregate PUSH_OK (ISSUE 19 relay contract)"
    ),
    ("sheeprl_tpu/serve/params.py", "ParamsStore.reload", "SY002"): (
        "by design: _reload_lock serializes checkpoint restores and is "
        "NEVER taken on the dispatch path — current() is a lock-free "
        "tuple read, so a slow orbax restore stalls only a second reload "
        "(PR 15 hot-reload contract)"
    ),
}

# analyzed packages (relative to the sheeprl_tpu package root)
DEFAULT_PACKAGES = (
    "flock",
    "serve",
    "telemetry",
    "resilience",
    "parallel",
    "compile",
)

_REPO = Path(__file__).resolve().parents[2]

# -- wire-protocol classification (derived from the pinned registry) ----------

_HANDSHAKE_OPEN = {"HELLO", "PROFILE", "RELAY_HELLO"}
_REPLY_KINDS = {
    "WELCOME",
    "PUSH_OK",
    "HEARTBEAT_OK",
    "WEIGHTS",
    "WEIGHTS_UNCHANGED",
    "ERROR",
    "RESPONSE",
    "SHED",
}
_SEND_FUNCS = {"send_frame", "send_json"}
_RECV_FUNCS = {"recv_frame", "recv_json"}

# -- blocking-call classification for SY002 -----------------------------------

_BLOCKING_SOCKET = {
    "sendall",
    "recv",
    "recv_into",
    "accept",
    "connect",
    "send_frame",
    "send_json",
    "recv_frame",
    "recv_json",
}
_BLOCKING_RESTORE = {"restore", "restore_checkpoint", "load_checkpoint"}


@dataclass
class Finding:
    rule: Rule
    path: str
    line: int
    qualname: str
    message: str
    suppressed: Optional[str] = None

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}: {self.rule.id} [{self.qualname}] "
            f"{self.message}{tag}"
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule.id,
            "path": self.path,
            "line": self.line,
            "qualname": self.qualname,
            "message": self.message,
            "suppressed": self.suppressed,
        }


@dataclass
class LockDef:
    ident: str
    kind: str  # Lock | RLock | Condition
    path: str
    line: int
    backing: Optional[str] = None  # Condition's shared backing lock identity

    @property
    def site(self) -> str:
        return f"{self.path}:{self.line}"

    @property
    def acq_ident(self) -> str:
        """Identity acquisitions are recorded under: a Condition built on
        a shared lock acquires THAT lock; otherwise itself."""
        return self.backing or self.ident


@dataclass
class ThreadDef:
    role: str
    path: str
    line: int
    target: str
    name: str  # literal, or template with `*` for interpolated parts
    daemon: Optional[bool]
    joined: bool = False
    subclass: bool = False

    def key(self) -> str:
        return f"{self.path}::{self.name}::{self.target}"

    def as_dict(self) -> dict:
        return {
            "path": self.path,
            "line": self.line,
            "target": self.target,
            "name": self.name,
            "daemon": self.daemon,
            "joined": self.joined,
            "subclass": self.subclass,
        }


@dataclass
class _Edge:
    src: str
    dst: str
    path: str
    line: int
    chain: str  # human-readable acquisition chain


@dataclass
class _FuncInfo:
    qualname: str  # "flock.service::ReplayService._handle_push"
    path: str
    cls: Optional[str]
    # acq ident -> first acquisition line (with-statements only)
    acquires: dict[str, int] = field(default_factory=dict)
    edges: list[_Edge] = field(default_factory=list)
    # every resolved call: (callee key, line, held idents at the call)
    calls: list[tuple[str, int, tuple[str, ...]]] = field(default_factory=list)
    # blocking leaf calls: (line, held idents at the call, description)
    blocking: list[tuple[int, tuple[str, ...], str]] = field(default_factory=list)
    receives: bool = False  # calls wire recv_frame / recv_json


class _ModuleAnalysis:
    """Single-file AST pass. Rule evaluation that needs the global picture
    (SY001 cycles, SY002 interprocedural, SY003 roots, SY006 handler
    reachability) happens in `ConcurrencyReport.link`."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        parts = Path(relpath).with_suffix("").parts
        if parts and parts[0] == "sheeprl_tpu":
            parts = parts[1:]
        self.mod = ".".join(parts)  # "flock.service"
        self.role = parts[0] if parts else relpath
        self.tree = ast.parse(source)
        self.aliases: dict[str, str] = {}
        self.locks: dict[str, LockDef] = {}
        self.threads: list[ThreadDef] = []
        self.funcs: dict[str, _FuncInfo] = {}
        # (class, attr) -> [(method name, line, held idents)]
        self.attr_writes: dict[tuple[str, str], list] = {}
        # class -> set of method names used as thread targets
        self.thread_targets: dict[str, set[str]] = {}
        self.class_methods: dict[str, set[str]] = {}
        self.findings: list[Finding] = []
        self._lock_valued_attrs: set[tuple[str, str]] = set()
        self._thread_stores: set[str] = set()
        self._thread_collections: set[str] = set()
        self._annotate_parents()
        self._collect_imports()

    # -- plumbing --------------------------------------------------------------

    def _annotate_parents(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._sync_parent = node  # type: ignore[attr-defined]

    def _collect_imports(self) -> None:
        pkg = ("sheeprl_tpu." + self.mod).rsplit(".", 1)[0]
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg
                    for _ in range(node.level - 1):
                        base = base.rsplit(".", 1)[0]
                    module = f"{base}.{node.module}" if node.module else base
                else:
                    module = node.module or ""
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{module}.{a.name}"

    def _dotted(self, node: ast.AST) -> Optional[str]:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(self.aliases.get(node.id, node.id))
            return ".".join(reversed(parts))
        return None

    @staticmethod
    def _leaf(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _parents(self, node: ast.AST):
        cur = getattr(node, "_sync_parent", None)
        while cur is not None:
            yield cur
            cur = getattr(cur, "_sync_parent", None)

    def _enclosing_class(self, node: ast.AST) -> Optional[str]:
        for p in self._parents(node):
            if isinstance(p, ast.ClassDef):
                return p.name
        return None

    @staticmethod
    def _store_name(target: ast.AST) -> Optional[str]:
        """`x` or `self.x` -> the bare name; anything else -> None."""
        if isinstance(target, ast.Name):
            return target.id
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            return target.attr
        return None

    def _finding(self, rule_id: str, node: ast.AST, qualname: str, message: str):
        self.findings.append(
            Finding(SY_RULES[rule_id], self.relpath, node.lineno, qualname, message)
        )

    # -- phase 1: definitions --------------------------------------------------

    def _lock_ctor_kind(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Call):
            dotted = self._dotted(node.func)
            if dotted in ("threading.Lock", "threading.RLock", "threading.Condition"):
                return dotted.rsplit(".", 1)[1]
        return None

    def collect_defs(self) -> None:
        pending: list[tuple[LockDef, ast.AST, Optional[str]]] = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                self._maybe_lock_def(node, pending)
            elif isinstance(node, ast.Call):
                self._maybe_thread_ctor(node)
            elif isinstance(node, ast.ClassDef):
                self.class_methods[node.name] = {
                    n.name
                    for n in node.body
                    if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                if any(self._dotted(b) == "threading.Thread" for b in node.bases):
                    self._thread_subclass(node)
        for ld, expr, cls in pending:
            ident = self._resolve_ident(expr, cls)
            if ident and ident in self.locks:
                ld.backing = self.locks[ident].acq_ident
        self._collect_joins()

    def _maybe_lock_def(self, node: ast.Assign, pending) -> None:
        target = node.targets[0]
        cls = self._enclosing_class(target)
        ident = None
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and cls
        ):
            ident = f"{self.mod}.{cls}.{target.attr}"
        elif isinstance(target, ast.Name) and cls is None:
            if not any(
                isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef))
                for p in self._parents(target)
            ):
                ident = f"{self.mod}.{target.id}"
        if ident is None:
            return
        kind = self._lock_ctor_kind(node.value)
        if kind:
            ld = LockDef(ident, kind, self.relpath, node.value.lineno)
            self.locks[ident] = ld
            if cls:
                self._lock_valued_attrs.add((cls, target.attr))
            if kind == "Condition" and node.value.args:  # type: ignore[union-attr]
                pending.append((ld, node.value.args[0], cls))  # type: ignore[union-attr]
        elif isinstance(node.value, ast.DictComp):
            kind = self._lock_ctor_kind(node.value.value)
            if kind:
                self.locks[f"{ident}[*]"] = LockDef(
                    f"{ident}[*]", kind, self.relpath, node.value.lineno
                )
                if cls:
                    self._lock_valued_attrs.add((cls, target.attr))
        elif self._dotted(getattr(node.value, "func", node.value)) in (
            "threading.Event",
            "threading.Semaphore",
            "threading.BoundedSemaphore",
            "threading.Barrier",
        ):
            if cls:
                self._lock_valued_attrs.add((cls, target.attr))

    def _maybe_thread_ctor(self, node: ast.Call) -> None:
        dotted = self._dotted(node.func)
        if dotted not in ("threading.Thread", "threading.Timer"):
            return
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        target_expr = kw.get("target")
        if target_expr is None and dotted == "threading.Timer" and len(node.args) >= 2:
            target_expr = node.args[1]
        target = self._dotted(target_expr) if target_expr is not None else None
        name = self._name_template(kw.get("name"))
        if dotted == "threading.Timer" and name == "?":
            name = "timer"
        daemon = None
        if "daemon" in kw and isinstance(kw["daemon"], ast.Constant):
            daemon = bool(kw["daemon"].value)
        self.threads.append(
            ThreadDef(
                role=self.role,
                path=self.relpath,
                line=node.lineno,
                target=target or "?",
                name=name,
                daemon=daemon,
            )
        )
        cls = self._enclosing_class(node)
        if target and target.startswith("self.") and cls:
            self.thread_targets.setdefault(cls, set()).add(target[5:])
        # remember where the thread object lands, for join matching
        parent = getattr(node, "_sync_parent", None)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            stored = self._store_name(parent.targets[0])
            if stored:
                self._thread_stores.add(stored)

    def _thread_subclass(self, node: ast.ClassDef) -> None:
        name, daemon = "?", None
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "__init__"
                and isinstance(sub.func.value, ast.Call)
                and self._leaf(sub.func.value.func) == "super"
            ):
                kw = {k.arg: k.value for k in sub.keywords if k.arg}
                name = self._name_template(kw.get("name"))
                if "daemon" in kw and isinstance(kw["daemon"], ast.Constant):
                    daemon = bool(kw["daemon"].value)
        self.threads.append(
            ThreadDef(
                role=self.role,
                path=self.relpath,
                line=node.lineno,
                target=f"{node.name}.run",
                name=name,
                daemon=daemon,
                subclass=True,
            )
        )
        self.thread_targets.setdefault(node.name, set()).add("run")

    @staticmethod
    def _name_template(node: Optional[ast.AST]) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            return "".join(
                str(p.value) if isinstance(p, ast.Constant) else "*"
                for p in node.values
            )
        return "?"

    def _collect_joins(self) -> None:
        join_receivers: set[str] = set()
        for node in ast.walk(self.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
            ):
                recv = self._store_name(node.func.value)
                if recv:
                    join_receivers.add(recv)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "append"
            ):
                coll = self._store_name(node.func.value)
                arg = self._store_name(node.args[0]) if node.args else None
                if coll and arg in self._thread_stores:
                    self._thread_collections.add(coll)
        # `for t in self._threads: t.join()` joins the collection
        for node in ast.walk(self.tree):
            if isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                it = node.iter
                if (
                    isinstance(it, ast.Call)
                    and self._leaf(it.func) == "list"
                    and it.args
                ):
                    it = it.args[0]
                src = self._store_name(it)
                if src in self._thread_collections and node.target.id in join_receivers:
                    join_receivers.add(src)
        joined_stores = (self._thread_stores | self._thread_collections) & join_receivers
        for td in self.threads:
            if joined_stores:
                td.joined = True

    # -- lock-expression resolution --------------------------------------------

    def _resolve_ident(self, node: ast.AST, cls: Optional[str]) -> Optional[str]:
        """`self._lock` / module `_gate` / `self._locks[i]` -> lock ident."""
        if isinstance(node, ast.Subscript):
            base = self._raw_ident(node.value, cls)
            if base and f"{base}[*]" in self.locks:
                return f"{base}[*]"
            return None
        ident = self._raw_ident(node, cls)
        return ident if ident in self.locks else None

    def _raw_ident(self, node: ast.AST, cls: Optional[str]) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and cls
        ):
            return f"{self.mod}.{cls}.{node.attr}"
        if isinstance(node, ast.Name):
            return f"{self.mod}.{node.id}"
        return None

    def lock_kind(self, acq_ident: str) -> Optional[str]:
        ld = self.locks.get(acq_ident)
        return ld.kind if ld else None

    # -- phase 2: function walks -----------------------------------------------

    def analyze_functions(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(node)

    def _qualname(self, func: ast.AST) -> str:
        names = [func.name]  # type: ignore[attr-defined]
        for p in self._parents(func):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.append(p.name)
        return ".".join(reversed(names))

    def _walk_function(self, func) -> None:
        cls = self._enclosing_class(func)
        qual = self._qualname(func)
        info = _FuncInfo(
            qualname=f"{self.mod}::{qual}", path=self.relpath, cls=cls
        )
        self.funcs[info.qualname] = info
        held: list[str] = []
        for stmt in func.body:
            self._visit(stmt, func, cls, qual, info, held)
        self._check_sy004(func, cls, qual)
        self._check_sy005(func, cls, qual)
        self._check_sy006_fresh(func, cls, qual)

    def _visit(self, node, func, cls, qual, info: _FuncInfo, held) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # separate walk / deferred execution
        if isinstance(node, ast.With):
            acquired: list[str] = []
            for item in node.items:
                self._visit(item.context_expr, func, cls, qual, info, held)
                ident = self._resolve_ident(item.context_expr, cls)
                if ident is None:
                    continue
                acq = self.locks[ident].acq_ident
                line = item.context_expr.lineno
                info.acquires.setdefault(acq, line)
                for outer in held:
                    if outer == acq:
                        if self.lock_kind(acq) == "Lock" and "[*]" not in acq:
                            self._finding(
                                "SY001",
                                item.context_expr,
                                qual,
                                f"nested re-acquisition of non-reentrant "
                                f"Lock `{acq}` self-deadlocks",
                            )
                        continue
                    info.edges.append(
                        _Edge(
                            src=outer,
                            dst=acq,
                            path=self.relpath,
                            line=line,
                            chain=(
                                f"{qual} holds {outer}, acquires {acq} at "
                                f"{self.relpath}:{line}"
                            ),
                        )
                    )
                held.append(acq)
                acquired.append(acq)
            for stmt in node.body:
                self._visit(stmt, func, cls, qual, info, held)
            for _ in acquired:
                held.pop()
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, cls, qual, info, held)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
            self._record_stores(node, cls, qual, info, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, func, cls, qual, info, held)

    def _visit_call(self, node: ast.Call, cls, qual, info: _FuncInfo, held) -> None:
        leaf = self._leaf(node.func)
        dotted = self._dotted(node.func)
        if leaf in _RECV_FUNCS:
            info.receives = True
        # resolved callee, for the interprocedural passes
        callee = self._callee_key(node, cls)
        if callee:
            info.calls.append((callee, node.lineno, tuple(held)))
        desc = self._blocking_desc(node, cls, leaf, dotted)
        if desc:
            # recorded even with no lock held: callers that DO hold one
            # inherit this through the interprocedural closure
            info.blocking.append((node.lineno, tuple(held), desc))

    def _callee_key(self, node: ast.Call, cls) -> Optional[str]:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
            and cls
            and f.attr in self.class_methods.get(cls, ())
        ):
            return f"{self.mod}::{cls}.{f.attr}"
        if isinstance(f, ast.Name):
            if f.id in self.class_methods:
                return None  # class constructor: __init__ rarely matters here
            dotted = self.aliases.get(f.id)
            if dotted and dotted.startswith("sheeprl_tpu."):
                mod, _, leaf = dotted.rpartition(".")
                return f"{mod.removeprefix('sheeprl_tpu.')}::{leaf}"
            return f"{self.mod}::{f.id}"
        dotted = self._dotted(f)
        if dotted and dotted.startswith("sheeprl_tpu."):
            mod, _, leaf = dotted.rpartition(".")
            return f"{mod.removeprefix('sheeprl_tpu.')}::{leaf}"
        return None

    def _blocking_desc(self, node: ast.Call, cls, leaf, dotted) -> Optional[str]:
        if dotted == "time.sleep":
            return "time.sleep"
        if dotted and dotted.startswith("subprocess."):
            return dotted
        if leaf in _BLOCKING_SOCKET:
            return f".{leaf}()" if leaf not in _SEND_FUNCS | _RECV_FUNCS else f"wire.{leaf}"
        if leaf == "send" and isinstance(node.func, ast.Attribute):
            recv = self._leaf(node.func.value) or ""
            if "sock" in recv or "conn" in recv:
                return ".send()"
            return None
        if leaf in _BLOCKING_RESTORE:
            return f".{leaf}()"
        if leaf and "loader" in leaf:
            return f"{leaf}() (checkpoint loader)"
        if leaf == "join":
            return self._join_blocking(node)
        if leaf == "wait" and isinstance(node.func, ast.Attribute):
            ident = self._resolve_ident(node.func.value, cls)
            if ident and self.locks[ident].kind == "Condition":
                return None  # Condition.wait releases its backing lock
            return ".wait() (Event/process)"
        return None

    def _join_blocking(self, node: ast.Call) -> Optional[str]:
        """Thread.join vs str.join: flag no-arg joins, timeout kwargs and
        single numeric/timeout-named args; skip `sep.join(iterable)`."""
        if isinstance(node.func, ast.Attribute) and isinstance(
            node.func.value, ast.Constant
        ):
            return None
        if any(k.arg == "timeout" for k in node.keywords):
            return "Thread.join"
        if not node.args and not node.keywords:
            return "Thread.join"
        if len(node.args) == 1:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, (int, float)):
                return "Thread.join"
            if isinstance(a, ast.Name) and any(
                h in a.id for h in ("timeout", "deadline", "left", "budget")
            ):
                return "Thread.join"
        return None

    def _record_stores(self, node, cls, qual, info: _FuncInfo, held) -> None:
        if cls is None or qual.split(".")[-1] == "__init__":
            return
        targets = (
            node.targets
            if isinstance(node, ast.Assign)
            else [node.target]
            if isinstance(node, ast.AugAssign)
            else node.targets
        )
        method = qual.split(".")[-1]
        flat: list[ast.AST] = []
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                flat.extend(t.elts)
            else:
                flat.append(t)
        for t in flat:
            base = t
            if isinstance(base, ast.Subscript):
                base = base.value
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                attr = base.attr
                if (cls, attr) in self._lock_valued_attrs:
                    continue
                self.attr_writes.setdefault((cls, attr), []).append(
                    (method, t.lineno, tuple(held))
                )

    # -- flat per-function rule passes ----------------------------------------

    def _check_sy004(self, func, cls, qual) -> None:
        for node in ast.walk(func):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                continue
            ident = self._resolve_ident(node.func.value, cls)
            if ident is None:
                continue
            recv = self._store_name(node.func.value)

            def releases(try_node: ast.Try) -> bool:
                for fin in try_node.finalbody:
                    for sub in ast.walk(fin):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"
                            and self._store_name(sub.func.value) == recv
                        ):
                            return True
                return False

            safe = False
            prev = node
            for p in self._parents(node):
                if isinstance(p, ast.Try) and releases(p):
                    safe = True
                # the canonical idiom puts the acquire BEFORE the Try:
                # `lock.acquire()` then `try: ... finally: lock.release()`
                # as the next statement in the same block
                for field in ("body", "orelse", "finalbody"):
                    stmts = getattr(p, field, None) or []
                    if prev in stmts:
                        idx = stmts.index(prev)
                        if (
                            idx + 1 < len(stmts)
                            and isinstance(stmts[idx + 1], ast.Try)
                            and releases(stmts[idx + 1])
                        ):
                            safe = True
                if p is func or safe:
                    break
                prev = p
            if not safe:
                self._finding(
                    "SY004",
                    node,
                    qual,
                    f"manual acquire of `{ident}` without a matching "
                    f"release in a finally block (use `with`)",
                )

    def _check_sy005(self, func, cls, qual) -> None:
        for node in ast.walk(func):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
            ):
                continue
            ident = self._resolve_ident(node.func.value, cls)
            if ident is None or self.locks[ident].kind != "Condition":
                continue
            in_loop = False
            for p in self._parents(node):
                if p is func:
                    break
                if isinstance(p, (ast.While, ast.For)):
                    in_loop = True
                    break
            if not in_loop:
                self._finding(
                    "SY005",
                    node,
                    qual,
                    f"`{ident}.wait()` outside a predicate re-checking loop "
                    f"(spurious wakeup / timeout returns unhandled)",
                )

    # -- SY006: within-function fresh-socket handshake order -------------------

    def _kind_const(self, node: ast.AST) -> Optional[str]:
        """`wire.HELLO` / imported `HELLO` -> "HELLO" when it looks like a
        frame-kind constant."""
        leaf = self._leaf(node)
        if leaf and leaf.isupper():
            return leaf
        return None

    def _calls_in_order(self, func) -> Iterable[ast.Call]:
        def rec(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.Call):
                yield node
            for child in ast.iter_child_nodes(node):
                yield from rec(child)

        for stmt in func.body:
            yield from rec(stmt)

    def sends_of(self, func) -> list[tuple[str, str, int]]:
        """Ordered (sock name, KIND, line) sends inside `func`."""
        out = []
        for call in self._calls_in_order(func):
            if self._leaf(call.func) in _SEND_FUNCS and len(call.args) >= 2:
                sock = self._store_name(call.args[0]) or "?"
                kind = self._kind_const(call.args[1])
                if kind:
                    out.append((sock, kind, call.lineno))
        return out

    def _check_sy006_fresh(self, func, cls, qual) -> None:
        fresh: dict[str, int] = {}
        sent_on: set[str] = set()
        for call in self._calls_in_order(func):
            dotted = self._dotted(call.func)
            leaf = self._leaf(call.func)
            if leaf == "connect" and dotted and (
                dotted.endswith("wire.connect") or dotted == "connect"
            ):
                parent = getattr(call, "_sync_parent", None)
                if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
                    stored = self._store_name(parent.targets[0])
                    if stored:
                        fresh[stored] = call.lineno
                continue
            if leaf in _SEND_FUNCS and len(call.args) >= 2:
                sock = self._store_name(call.args[0])
                kind = self._kind_const(call.args[1])
                if sock is None or kind is None:
                    continue
                if sock in fresh and sock not in sent_on:
                    sent_on.add(sock)
                    if kind not in _HANDSHAKE_OPEN:
                        self._finding(
                            "SY006",
                            call,
                            qual,
                            f"first frame on fresh connection `{sock}` "
                            f"(wire.connect at line {fresh[sock]}) is {kind}, "
                            f"not HELLO/PROFILE",
                        )


# -- global linking ------------------------------------------------------------


@dataclass
class ConcurrencyReport:
    modules: list[_ModuleAnalysis]
    findings: list[Finding] = field(default_factory=list)
    locks: dict[str, LockDef] = field(default_factory=dict)
    threads: list[ThreadDef] = field(default_factory=list)
    # (src, dst) -> representative chain text
    edges: dict[tuple[str, str], str] = field(default_factory=dict)
    cycles: list[tuple[str, str, str, str]] = field(default_factory=list)
    # role -> {"Class.attr" -> guard ident | None} (shared attrs only)
    guards: dict[str, dict[str, Optional[str]]] = field(default_factory=dict)

    # -- linking ---------------------------------------------------------------

    def link(self) -> None:
        funcs: dict[str, _FuncInfo] = {}
        for m in self.modules:
            self.locks.update(m.locks)
            self.threads.extend(m.threads)
            self.findings.extend(m.findings)
            funcs.update(m.funcs)
        self._link_edges(funcs)
        self._check_cycles()
        self._check_blocking(funcs)
        self._check_shared_writes(funcs)
        self._check_reply_contexts(funcs)
        self._apply_suppressions()
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule.id))

    def _lock_kind(self, acq: str) -> Optional[str]:
        ld = self.locks.get(acq)
        return ld.kind if ld else None

    def _link_edges(self, funcs: dict[str, _FuncInfo]) -> None:
        # transitive acquires: acq ident -> representative site, per function
        closure: dict[str, dict[str, str]] = {
            q: {a: f"{fi.path}:{line} in {q.split('::')[-1]}" for a, line in fi.acquires.items()}
            for q, fi in funcs.items()
        }
        changed = True
        while changed:
            changed = False
            for q, fi in funcs.items():
                mine = closure[q]
                for callee, _line, _held in fi.calls:
                    for a, site in closure.get(callee, {}).items():
                        if a not in mine:
                            mine[a] = site
                            changed = True
        for q, fi in funcs.items():
            for e in fi.edges:
                self.edges.setdefault((e.src, e.dst), e.chain)
            for callee, line, held in fi.calls:
                if not held:
                    continue
                for acq, site in closure.get(callee, {}).items():
                    for h in held:
                        if h == acq:
                            if self._lock_kind(acq) == "Lock" and "[*]" not in acq:
                                self.findings.append(
                                    Finding(
                                        SY_RULES["SY001"],
                                        fi.path,
                                        line,
                                        q.split("::")[-1],
                                        f"holds non-reentrant Lock `{acq}` "
                                        f"across call to {callee.split('::')[-1]} "
                                        f"which re-acquires it ({site}): "
                                        f"self-deadlock",
                                    )
                                )
                            continue
                        chain = (
                            f"{q.split('::')[-1]} holds {h}, calls "
                            f"{callee.split('::')[-1]} at {fi.path}:{line} "
                            f"which acquires {acq} ({site})"
                        )
                        self.edges.setdefault((h, acq), chain)

    def _check_cycles(self) -> None:
        adj: dict[str, set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)

        def reaches(src: str, dst: str) -> bool:
            seen, stack = set(), [src]
            while stack:
                n = stack.pop()
                if n == dst:
                    return True
                if n in seen:
                    continue
                seen.add(n)
                stack.extend(adj.get(n, ()))
            return False

        reported: set[frozenset] = set()
        for (a, b), chain in sorted(self.edges.items()):
            if reaches(b, a):
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                back = self.edges.get((b, a), f"(via intermediate locks) {b} .. {a}")
                self.cycles.append((a, b, chain, back))
                path = chain.split(" at ")[-1].split(" ")[0]
                self.findings.append(
                    Finding(
                        SY_RULES["SY001"],
                        self.locks[a].path if a in self.locks else path,
                        self.locks[a].line if a in self.locks else 0,
                        "<lock-graph>",
                        f"lock-order cycle between `{a}` and `{b}`: "
                        f"[chain 1] {chain}; [chain 2] {back}",
                    )
                )

    def _check_blocking(self, funcs: dict[str, _FuncInfo]) -> None:
        for q, fi in funcs.items():
            for line, held, desc in fi.blocking:
                if not held:
                    continue
                self.findings.append(
                    Finding(
                        SY_RULES["SY002"],
                        fi.path,
                        line,
                        q.split("::")[-1],
                        f"blocking {desc} while holding {', '.join(held)}",
                    )
                )
        # interprocedural: calls made while holding a lock, into functions
        # whose closure contains blocking calls
        blocking_any: dict[str, list[tuple[str, str]]] = {}
        for q, fi in funcs.items():
            items = [
                (d, f"{fi.path}:{line}")
                for line, d in [(l, d) for l, _h, d in fi.blocking]
            ]
            blocking_any[q] = items
        full: dict[str, list[tuple[str, str]]] = {}

        def collect(q: str, seen: set[str]) -> list[tuple[str, str]]:
            if q in full:
                return full[q]
            if q in seen:
                return []
            seen.add(q)
            out = list(blocking_any.get(q, ()))
            for callee, _line, _held in funcs.get(q, _FuncInfo(q, "", None)).calls:
                out.extend(collect(callee, seen))
            full[q] = out[:4]
            return full[q]

        for q in list(funcs):
            collect(q, set())
        for q, fi in funcs.items():
            for callee, line, held in fi.calls:
                if not held or callee not in funcs:
                    continue
                for desc, site in full.get(callee, ()):
                    self.findings.append(
                        Finding(
                            SY_RULES["SY002"],
                            fi.path,
                            line,
                            q.split("::")[-1],
                            f"call to {callee.split('::')[-1]} while holding "
                            f"{', '.join(held)} reaches blocking {desc} "
                            f"({site})",
                        )
                    )

    def _check_shared_writes(self, funcs: dict[str, _FuncInfo]) -> None:
        for m in self.modules:
            # class-internal call graph: method -> same-class methods called
            calls: dict[str, dict[str, set[str]]] = {}
            for q, fi in m.funcs.items():
                if fi.cls is None:
                    continue
                qual = q.split("::")[-1]
                if "." not in qual:
                    continue
                cls, method = qual.rsplit(".", 1)
                for callee, _l, _h in fi.calls:
                    cq = callee.split("::")[-1]
                    if cq.startswith(f"{cls}."):
                        calls.setdefault(cls, {}).setdefault(method, set()).add(
                            cq.rsplit(".", 1)[1]
                        )
            for cls in m.class_methods:
                targets = m.thread_targets.get(cls, set())
                roots: dict[str, set[str]] = {}
                for t in targets:
                    roots[f"thread:{t}"] = self._reach(calls.get(cls, {}), t)
                api_entry = {
                    meth
                    for meth in m.class_methods[cls]
                    if not meth.startswith("_") or meth in ("__enter__", "__exit__")
                } - targets
                api_reach: set[str] = set()
                for meth in api_entry:
                    api_reach |= self._reach(calls.get(cls, {}), meth)
                if api_reach:
                    roots["api"] = api_reach
                if len(roots) < 2:
                    continue
                for (wcls, attr), writes in m.attr_writes.items():
                    if wcls != cls:
                        continue
                    writer_roots = {
                        rname
                        for rname, reach in roots.items()
                        for method, _line, _held in writes
                        if method in reach
                    }
                    if len(writer_roots) < 2:
                        continue
                    common = None
                    for _method, _line, held in writes:
                        s = set(held)
                        common = s if common is None else (common & s)
                    guard = sorted(common)[0] if common else None
                    role = m.role
                    self.guards.setdefault(role, {})[f"{cls}.{attr}"] = guard
                    if guard is None:
                        wsites = ", ".join(
                            f"{meth}:{line}" for meth, line, _h in writes[:4]
                        )
                        self.findings.append(
                            Finding(
                                SY_RULES["SY003"],
                                m.relpath,
                                writes[0][1],
                                f"{cls}.{attr}",
                                f"written from {len(writer_roots)} thread "
                                f"entry points ({', '.join(sorted(writer_roots))}) "
                                f"with no common guard; writes at {wsites}",
                            )
                        )

    @staticmethod
    def _reach(graph: dict[str, set[str]], start: str) -> set[str]:
        seen, stack = set(), [start]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return seen

    def _check_reply_contexts(self, funcs: dict[str, _FuncInfo]) -> None:
        handlers = {q for q, fi in funcs.items() if fi.receives}
        changed = True
        while changed:
            changed = False
            for q in list(handlers):
                for callee, _l, _h in funcs.get(q, _FuncInfo(q, "", None)).calls:
                    if callee in funcs and callee not in handlers:
                        handlers.add(callee)
                        changed = True
        for m in self.modules:
            for node in ast.walk(m.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                qual = m._qualname(node)
                q = f"{m.mod}::{qual}"
                if q in handlers:
                    continue
                for sock, kind, line in m.sends_of(node):
                    if kind in _REPLY_KINDS:
                        m_find = Finding(
                            SY_RULES["SY006"],
                            m.relpath,
                            line,
                            qual,
                            f"reply kind {kind} sent outside a request "
                            f"handler (no recv_frame/recv_json on the call "
                            f"path into {qual})",
                        )
                        self.findings.append(m_find)

    def _apply_suppressions(self) -> None:
        for f in self.findings:
            just = SYNC_SUPPRESSIONS.get(
                (f.path, f.qualname, f.rule.id)
            ) or SYNC_SUPPRESSIONS.get((f.path, "*", f.rule.id))
            if just:
                f.suppressed = just

    # -- views -----------------------------------------------------------------

    @property
    def active_findings(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed_findings(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]


# -- entry points --------------------------------------------------------------


def default_paths() -> list[str]:
    return [str(_REPO / "sheeprl_tpu" / pkg) for pkg in DEFAULT_PACKAGES]


def analyze_paths(paths: Optional[Iterable[str]] = None) -> ConcurrencyReport:
    modules = []
    for path in iter_python_files(paths or default_paths()):
        p = Path(path).resolve()
        try:
            rel = str(p.relative_to(_REPO))
        except ValueError:
            rel = str(p)
        with open(p, encoding="utf-8") as fh:
            src = fh.read()
        m = _ModuleAnalysis(str(p), rel, src)
        m.collect_defs()
        m.analyze_functions()
        modules.append(m)
    report = ConcurrencyReport(modules=modules)
    report.link()
    return report


def analyze_source(source: str, relpath: str = "fixture.py") -> ConcurrencyReport:
    """Single-source entry for tests/fixtures."""
    m = _ModuleAnalysis(relpath, relpath, source)
    m.collect_defs()
    m.analyze_functions()
    report = ConcurrencyReport(modules=[m])
    report.link()
    return report


# -- ledger --------------------------------------------------------------------


def ledger_path() -> Path:
    return _REPO / "analysis" / "budget" / "concurrency.json"


def build_ledger(report: ConcurrencyReport) -> dict:
    roles: dict[str, dict] = {}
    for m in report.modules:
        role = roles.setdefault(
            m.role, {"locks": {}, "threads": [], "guards": {}}
        )
        for ident, ld in sorted(m.locks.items()):
            role["locks"][ident] = {
                "kind": ld.kind,
                "site": ld.site,
                "backing": ld.backing,
            }
        for td in m.threads:
            role["threads"].append(td.as_dict())
    for role, guards in report.guards.items():
        roles.setdefault(role, {"locks": {}, "threads": [], "guards": {}})[
            "guards"
        ] = dict(sorted(guards.items()))
    for role in roles.values():
        role["threads"].sort(key=lambda t: (t["path"], t["line"]))
    edges = sorted([list(e) for e in report.edges])
    lock_sites = {
        ld.site: ld.ident for ld in sorted(report.locks.values(), key=lambda l: l.site)
    }
    canonical = json.dumps(
        {
            "edges": edges,
            "guards": {r: roles[r]["guards"] for r in sorted(roles)},
            "threads": sorted(
                td.key() for m in report.modules for td in m.threads
            ),
        },
        sort_keys=True,
    )
    fingerprint = hashlib.sha256(canonical.encode()).hexdigest()[:16]
    return {
        "concurrency": {
            "version": 1,
            "fingerprint": fingerprint,
            "lock_order": {
                "edges": edges,
                "chains": {f"{a} -> {b}": c for (a, b), c in sorted(report.edges.items())},
                "cycles": [list(c[:2]) for c in report.cycles],
            },
            "lock_sites": lock_sites,
            "roles": {r: roles[r] for r in sorted(roles)},
        }
    }


def save_ledger(ledger: dict, path: Optional[Path] = None) -> Path:
    path = path or ledger_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(ledger, indent=2, sort_keys=True) + "\n")
    return path


def load_ledger(path: Optional[Path] = None) -> Optional[dict]:
    path = path or ledger_path()
    if not path.exists():
        return None
    return json.loads(path.read_text())


def check_budget(current: dict, committed: Optional[dict]) -> list[str]:
    """Drift gate: regressions of `current` vs the committed ledger.
    Returns human-readable regression lines (empty = pass)."""
    if committed is None:
        return ["no committed ledger at analysis/budget/concurrency.json — run tools/sheepsync.py --update-budget"]
    cur = current["concurrency"]
    old = committed.get("concurrency", {})
    out: list[str] = []
    old_edges = {tuple(e) for e in old.get("lock_order", {}).get("edges", [])}
    chains = cur["lock_order"].get("chains", {})
    for e in cur["lock_order"]["edges"]:
        if tuple(e) not in old_edges:
            chain = chains.get(f"{e[0]} -> {e[1]}", "")
            out.append(
                f"new lock-order edge {e[0]} -> {e[1]}"
                + (f" [{chain}]" if chain else "")
            )
    for cyc in cur["lock_order"].get("cycles", []):
        out.append(f"lock-order cycle {cyc[0]} <-> {cyc[1]}")
    old_roles = old.get("roles", {})
    for role, data in cur.get("roles", {}).items():
        old_guards = old_roles.get(role, {}).get("guards", {})
        for attr, guard in data.get("guards", {}).items():
            if guard is None and old_guards.get(attr, "absent") is not None:
                out.append(
                    f"newly unguarded shared write: {role}:{attr} "
                    f"(no common lock dominates every writer)"
                )
        old_threads = {
            (t["path"], t["name"], t["target"])
            for t in old_roles.get(role, {}).get("threads", [])
        }
        for t in data.get("threads", []):
            if (t["path"], t["name"], t["target"]) not in old_threads:
                out.append(
                    f"new undeclared thread {t['name']!r} "
                    f"(target {t['target']}) at {t['path']}:{t['line']}"
                )
    return out


# -- rendering -----------------------------------------------------------------


def render_report(report: ConcurrencyReport) -> str:
    lines = ["sheepsync lock-order report", "=" * 60]
    by_role: dict[str, list[LockDef]] = {}
    for m in report.modules:
        by_role.setdefault(m.role, []).extend(m.locks.values())
    for role in sorted(by_role):
        lines.append(f"\n[{role}] locks:")
        for ld in sorted(by_role[role], key=lambda l: l.ident):
            extra = f" on {ld.backing}" if ld.backing else ""
            lines.append(f"  {ld.ident:55s} {ld.kind}{extra}  ({ld.site})")
    lines.append("\nlock-order edges (outer -> inner):")
    if not report.edges:
        lines.append("  (none)")
    for (a, b), chain in sorted(report.edges.items()):
        lines.append(f"  {a} -> {b}")
        lines.append(f"      {chain}")
    if report.cycles:
        lines.append("\nCYCLES:")
        for a, b, c1, c2 in report.cycles:
            lines.append(f"  {a} <-> {b}")
            lines.append(f"      chain 1: {c1}")
            lines.append(f"      chain 2: {c2}")
    lines.append("\nthreads:")
    for m in report.modules:
        for td in m.threads:
            j = "joined" if td.joined else "unjoined"
            d = {True: "daemon", False: "non-daemon", None: "daemon?"}[td.daemon]
            lines.append(
                f"  {td.name:28s} target={td.target:40s} {d:11s} {j}  "
                f"({td.path}:{td.line})"
            )
    lines.append("\nguard map (attributes written from >=2 thread roots):")
    for role in sorted(report.guards):
        for attr, guard in sorted(report.guards[role].items()):
            lines.append(f"  [{role}] {attr:45s} -> {guard or 'UNGUARDED'}")
    return "\n".join(lines)
