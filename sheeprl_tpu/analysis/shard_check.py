"""sheepshard: SPMD partitioning & collective-communication analysis over
the lowered CompilePlan.

sheepcheck (jaxpr_check.py) audits every registered jit at the jaxpr level,
but the jaxpr is the program BEFORE XLA's SPMD partitioner runs — it is
blind to the thing that actually decides TPU scaling: how each jit shards
over the mesh and what collectives GSPMD inserts. Podracer
(arXiv:2104.06272) and MSRL (arXiv:2210.00882) both show that TPU-RL
throughput is won or lost in the placement/communication structure. This
module closes that gap: every mesh-bearing registered jit is lowered AND
compiled under its declared mesh (CPU, the virtual 8-device harness, zero
execution — `lower().compile()` builds the partitioned module without
running it), and the post-partitioning HLO text is parsed into a per-jit
**comms ledger**: every collective op (all-reduce / all-gather /
reduce-scatter / collective-permute / all-to-all), its operand/result
bytes, replica groups, whether it sits inside a while/scan body (where it
multiplies by the trip count), and an estimated bytes-on-the-wire per
dispatch under a ring-algorithm model.

Rule catalog (continues sheepcheck's SC numbering; suppressions live in
`SHARD_SUPPRESSIONS`, keyed `(spec, jit, rule)`, justification mandatory —
SC009 is source-level and uses sheeplint's `# sheeplint: disable=SC009`
comment syntax instead):

  SC006  collective inside a hot-loop (while/scan) body of a registered
         jit — the while's trip count multiplies the per-step comms; a
         gradient all-reduce per minibatch is a design decision that must
         be visible (and suppressed with its justification), an accidental
         one is a scaling cliff.
  SC007  silent full replication — an input the example thunk left
         UNSPECIFIED (no committed sharding) that the partitioner chose to
         fully replicate over a >1-device mesh, above a size floor:
         wasted HBM on every device plus an all-gather-shaped transfer on
         update. Declared (committed P()) replication is intentional and
         exempt — the rule targets layouts nobody chose.
  SC008  resharding thrash on a declared CompilePlan data edge — the
         producer jit's compiled output sharding disagrees with the
         consumer jit's compiled input sharding on an `expect="match"`
         edge, so every handoff pays an implicit reshard. This cross-jit
         contract check is the first concrete slice of the ROADMAP-4
         fragment graph.
  SC009  collective issued from an un-jitted host loop — an eager
         `jax.lax.psum`-family or `multihost_utils` call lexically inside
         a Python loop and outside any jit context pays one dispatch of a
         one-collective program per iteration (source-level AST pass,
         shares sheeplint's engine).

Fingerprints (collective histogram, hot-loop histogram, wire bytes,
silently-replicated inputs, per-edge sharding contracts) are committed to
the `analysis/budget/` ledger next to sheepcheck's compile-cost
fingerprints, and `tools/sheepshard.py --check-budget` is the CI drift
gate: a new collective kind, a new/multiplied hot-loop collective,
comms-bytes growth past tolerance, a newly replicated large tensor, or a
match-edge flipping to mismatch fails the build; reductions are notes.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Any, Iterable, Iterator

from .rules import Rule
from . import jaxpr_check as jc

__all__ = [
    "SHARD_RULES",
    "SHARD_SUPPRESSIONS",
    "SHARD_SWEEP",
    "Collective",
    "ShardReport",
    "analyze_entry",
    "analyze_shard_plan",
    "build_comms_budget",
    "check_comms_budget",
    "check_source_collectives",
    "comms_fingerprint",
    "estimate_wire_bytes",
    "parse_hlo_comms",
    "resolve_capture",
    "resolve_edges",
    "spec_key",
]

ERROR = "error"
WARNING = "warning"

_SHARD_RULES = [
    Rule(
        id="SC006",
        name="collective-in-hot-loop",
        severity=WARNING,
        summary=(
            "collective op inside a while/scan body of a registered jit — "
            "the loop's trip count multiplies the per-step communication, "
            "so one all-gather in a rollout scan is T all-gathers per "
            "dispatch"
        ),
        autofix=(
            "restructure so the collective runs once outside the loop "
            "(reduce locally, combine after the scan), or suppress with "
            "the design justification (a per-minibatch gradient all-reduce "
            "is the data-parallel minimum)"
        ),
    ),
    Rule(
        id="SC007",
        name="silent-full-replication",
        severity=WARNING,
        summary=(
            "large input with NO declared sharding that the SPMD "
            "partitioner fully replicated over a multi-device mesh — "
            "every device holds the whole tensor (wasted HBM) and updates "
            "pay replication traffic nobody asked for"
        ),
        autofix=(
            "commit the array with an explicit sharding (shard_batch / "
            "shard_env_batch / NamedSharding on the example spec), or make "
            "the replication explicit with a committed P() so the ledger "
            "records it as chosen"
        ),
    ),
    Rule(
        id="SC008",
        name="resharding-thrash",
        severity=WARNING,
        summary=(
            "producer jit's output sharding disagrees with the consumer "
            "jit's input sharding on a declared expect='match' data edge — "
            "every handoff forces an implicit reshard (all-gather + "
            "re-slice) XLA inserts silently at dispatch"
        ),
        autofix=(
            "align the two jits' shardings (usually: make the consumer's "
            "example thunk carry the producer's output sharding), or "
            "declare the edge expect='reshard' if the reshuffle is the "
            "documented contract"
        ),
    ),
    Rule(
        id="SC009",
        name="collective-in-host-loop",
        severity=WARNING,
        summary=(
            "eager collective (jax.lax.psum family / multihost_utils) "
            "called from an un-jitted Python loop — each iteration "
            "dispatches a one-collective program with full host-side "
            "dispatch overhead"
        ),
        autofix=(
            "move the loop under jit (lax.scan/fori_loop) so the "
            "collectives fuse into one program, or hoist the collective "
            "out of the loop; suppress with `# sheeplint: disable=SC009` "
            "plus justification for intentional per-iteration syncs"
        ),
    ),
]

SHARD_RULES: dict[str, Rule] = {r.id: r for r in _SHARD_RULES}

# (spec, jit, rule) -> justification; same contract as jaxpr_check's
# SUPPRESSIONS: a matching finding is reported as suppressed, not failing,
# and the justification is printed in verbose output.
SHARD_SUPPRESSIONS: dict[tuple[str, str, str], str] = {
    # The PPO update scans epochs x minibatches INSIDE one jit; under data
    # parallelism each minibatch's gradient all-reduce therefore sits in
    # the scan body. That is the data-parallel minimum (one grad-sized
    # all-reduce per minibatch, same count as the reference's per-step DDP
    # all-reduce) — the ledger locks the histogram so any ADDITIONAL
    # hot-loop collective still fails the gate.
    ("ppo@mesh8", "train_step", "SC006"): (
        "per-minibatch gradient all-reduce inside the epoch/minibatch scan "
        "is the data-parallel design minimum"
    ),
    ("ppo@anakin", "train_step", "SC006"): (
        "per-minibatch gradient all-reduce inside the epoch/minibatch scan "
        "is the data-parallel design minimum"
    ),
    # Under context parallelism the imagination scan runs over [T*B] rows
    # sharded across the FULL (data, seq) grid (the replicated-RSSM layout
    # measured fastest in MULTICHIP_r02), so its per-step actor/head
    # reductions all-reduce across the grid inside the scan body by
    # construction. The ledger locks the hot histogram: any ADDITIONAL
    # hot-loop collective still fails the comms gate.
    ("dreamer_v3@seq", "train_step", "SC006"): (
        "imagination-scan reductions over the fully-grid-sharded [T*B] "
        "rows are the chosen context-parallel layout (MULTICHIP_r02)"
    ),
}

_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# ---------------------------------------------------------------------------
# HLO text parsing: computations, loop bodies, collective instructions
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s32": 4, "u32": 4, "f32": 4, "c64": 8,
    "s64": 8, "u64": 8, "f64": 8, "c128": 16,
}

_SHAPE_RE = re.compile(
    r"\b(pred|f8e4m3fn|f8e5m2|f8e4m3b11fnuz|bf16|f16|f32|f64|c64|c128|"
    r"s4|s8|s16|s32|s64|u4|u8|u16|u32|u64)\[([0-9,]*)\]"
)

# `%name (params) -> result {` and `ENTRY %name (params) -> result {`
_COMP_HDR_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")

_COLL_RE = re.compile(
    r"=\s*(?P<rtype>\([^=]*?\)|\S+)\s+"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\("
)

_CALLED_RE = re.compile(r"(?:to_apply|body|condition|calls)=%?([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")
_TRIP_RE = re.compile(r'known_trip_count.{0,4}?[":{n]*"?(\d+)"')


def _shape_bytes(text: str) -> int:
    """Sum of array bytes over every `dtype[dims]` token in `text` (a type
    string — handles tuple types by summing elements)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def _replica_groups(attrs: str, num_partitions: int) -> tuple[int, int]:
    """Parse `replica_groups` in either syntax into (groups, group_size):
    the iota form `[G,S]<=[N]` or the explicit `{{0,1},{2,3}}` form."""
    m = re.search(r"replica_groups=\[(\d+),(\d+)\]<=\[\d+\]", attrs)
    if m:
        return int(m.group(1)), int(m.group(2))
    m = re.search(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}", attrs)
    if m:
        groups = m.group(1).split("},{")
        sizes = [
            len([t for t in g.strip("{}").split(",") if t.strip()]) for g in groups
        ]
        return len(groups), (max(sizes) if sizes else 1)
    return 1, max(num_partitions, 1)


def estimate_wire_bytes(
    kind: str, result_bytes: int, operand_bytes: int, groups: int, group_size: int
) -> int:
    """Estimated total bytes crossing the interconnect per dispatch of one
    collective, ring-algorithm model. HLO shapes are per-participant, and
    the LARGER of operand/result is the full logical payload (all-gather's
    result, reduce-scatter's operand, all-reduce's both):

      all-reduce      2*(s-1)*B   (reduce-scatter + all-gather phases)
      all-gather        (s-1)*B   (each device receives (s-1)/s of B)
      reduce-scatter    (s-1)*B   (mirror of all-gather)
      all-to-all        (s-1)*B   (each device keeps 1/s of its buffer)
      collective-permute  s * B   (each participant ships its buffer)

    multiplied by the number of disjoint replica groups."""
    full = max(result_bytes, operand_bytes)
    s = max(group_size, 1)
    if kind == "all-reduce":
        per_group = 2 * (s - 1) * full
    elif kind == "collective-permute":
        per_group = s * full
    else:
        per_group = (s - 1) * full
    return max(groups, 1) * per_group


@dataclasses.dataclass
class Collective:
    """One collective instruction of a partitioned HLO module."""

    kind: str
    name: str
    result_bytes: int
    operand_bytes: int
    groups: int
    group_size: int
    wire_bytes: int  # per dispatch of the enclosing computation
    hot: bool = False  # inside a while/scan body computation
    trip_count: int | None = None  # known_trip_count of the enclosing loop

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def parse_hlo_comms(text: str) -> dict:
    """Parse a post-partitioning HLO module (Compiled.as_text()) into its
    communication structure: `num_partitions`, and every collective with
    bytes, replica groups, and hot-loop placement (a collective is `hot`
    when its computation is reachable from a `while` body/condition —
    loop trip counts from XLA's `known_trip_count` when printed)."""
    lines = text.splitlines()
    header = lines[0] if lines else ""
    m = re.search(r"num_partitions=(\d+)", header)
    num_partitions = int(m.group(1)) if m else 1

    comps: dict[str, list[str]] = {}
    cur: str | None = None
    for line in lines[1:]:
        hdr = _COMP_HDR_RE.match(line)
        if hdr is not None:
            cur = hdr.group(1)
            comps[cur] = []
            continue
        if line.startswith("}"):
            cur = None
            continue
        if cur is not None:
            comps[cur].append(line)

    called: dict[str, set[str]] = {name: set() for name in comps}
    loop_roots: list[tuple[str, int | None]] = []  # (body/cond comp, trip)
    for name, body in comps.items():
        for line in body:
            refs = set(_CALLED_RE.findall(line))
            for blob in _BRANCHES_RE.findall(line):
                refs |= {b.strip().lstrip("%") for b in blob.split(",") if b.strip()}
            called[name] |= refs & set(comps)
            if " while(" in line:
                trip_m = _TRIP_RE.search(line)
                trip = int(trip_m.group(1)) if trip_m else None
                for key in ("body", "condition"):
                    km = re.search(rf"{key}=%?([\w.\-]+)", line)
                    if km and km.group(1) in comps:
                        loop_roots.append((km.group(1), trip))

    # transitive closure: everything reachable from a loop body is hot;
    # keep the largest known trip count on the path (0 = unknown)
    hot_trip: dict[str, int] = {}
    stack = [(name, trip or 0) for name, trip in loop_roots]
    while stack:
        name, trip = stack.pop()
        if name in hot_trip and hot_trip[name] >= trip:
            continue
        hot_trip[name] = trip
        for callee in called.get(name, ()):
            stack.append((callee, trip))

    collectives: list[Collective] = []
    for name, body in comps.items():
        hot = name in hot_trip
        trip = hot_trip.get(name) or None
        for line in body:
            m = _COLL_RE.search(line)
            if m is None:
                continue
            rest = line[m.end():]
            depth = 1
            for i, ch in enumerate(rest):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        break
            operands, attrs = rest[:i], rest[i + 1:]
            kind = m.group("kind")
            if kind == "collective-permute":
                pairs = re.search(r"source_target_pairs=\{(.*?)\}\}", attrs)
                npairs = pairs.group(1).count("{") + 1 if pairs else num_partitions
                groups, group_size = npairs, 1
            else:
                groups, group_size = _replica_groups(attrs, num_partitions)
            result_bytes = _shape_bytes(m.group("rtype"))
            operand_bytes = _shape_bytes(operands)
            collectives.append(
                Collective(
                    kind=kind,
                    name=name,
                    result_bytes=result_bytes,
                    operand_bytes=operand_bytes,
                    groups=groups,
                    group_size=group_size,
                    wire_bytes=estimate_wire_bytes(
                        kind, result_bytes, operand_bytes, groups, group_size
                    ),
                    hot=hot,
                    trip_count=trip,
                )
            )
    return {"num_partitions": num_partitions, "collectives": collectives}


# ---------------------------------------------------------------------------
# sharding introspection
# ---------------------------------------------------------------------------


def spec_key(sharding: Any) -> str:
    """A stable, human-readable key for a sharding: 'unspecified',
    'replicated', or `P(spec)@(mesh axes)` — what the ledger commits and
    the SC008 contract compares."""
    if sharding is None:
        return "unspecified"
    if sharding is _UNUSED:
        return "unused"
    if getattr(sharding, "is_fully_replicated", False):
        return "replicated"
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is not None and mesh is not None:
        axes = ",".join(f"{k}={v}" for k, v in dict(mesh.shape).items())
        return f"P{tuple(spec)}@({axes})"
    # GSPMDSharding (what the partitioner reports for inputs nobody
    # declared): the HLO tile assignment is the readable, stable part
    hlo = getattr(sharding, "_hlo_sharding", None)
    if hlo is not None:
        return f"hlo:{hlo}"
    return repr(sharding)[:120]


_UNUSED = object()  # flat input dropped by XLA's dead-arg elimination


def _flat_input_shardings(compiled: Any, n: int) -> list[Any]:
    """The compiled executable's per-flat-argument shardings, length `n`
    (the jaxpr's flat arity). XLA prunes unused arguments and
    `input_shardings` covers only the kept ones, so dropped positions are
    re-aligned via the executable's kept_var_idx and marked `_UNUSED` (an
    unused input imposes no layout constraint). None = introspection
    failed."""
    import jax

    try:
        args_sh, _ = compiled.input_shardings
        flat = list(jax.tree_util.tree_leaves(args_sh))
    except Exception:
        return [None] * n
    if len(flat) == n:
        return flat
    kept = getattr(getattr(compiled, "_executable", None), "_kept_var_idx", None)
    if kept is not None and len(kept) == len(flat):
        out: list[Any] = [_UNUSED] * n
        for idx, sh in zip(sorted(kept), flat):
            if idx < n:
                out[idx] = sh
        return out
    return [None] * n


def _flat_output_shardings(compiled: Any, n: int) -> list[Any]:
    import jax

    try:
        flat = list(jax.tree_util.tree_leaves(compiled.output_shardings))
    except Exception:
        flat = []
    if len(flat) != n:
        return [None] * n
    return flat


def _declared_shardings(specs: Any) -> list[Any]:
    """Per-flat-leaf sharding the example thunk DECLARED (None for leaves
    the main left unspecified — python scalars, uncommitted arrays)."""
    import jax

    out = []
    for leaf in jax.tree_util.tree_leaves(specs):
        out.append(getattr(leaf, "sharding", None))
    return out


def _mesh_axes(shardings: Iterable[Any]) -> dict[str, int]:
    """The (first) multi-device mesh named by any declared sharding."""
    for s in shardings:
        mesh = getattr(s, "mesh", None)
        if mesh is not None and getattr(mesh, "size", 1) > 1:
            return {str(k): int(v) for k, v in dict(mesh.shape).items()}
    return {}


def _replicated_floor() -> int:
    try:
        return int(
            os.environ.get("SHEEPRL_TPU_SHARD_REPLICATED_FLOOR", str(1 << 20))
        )
    except ValueError:
        return 1 << 20


def _aval_bytes(aval: Any) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(getattr(dtype, "itemsize", 4))


# ---------------------------------------------------------------------------
# per-entry analysis
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ShardReport:
    spec: str
    name: str
    comms: dict | None = None  # the committed comms fingerprint
    in_avals: list[str] = dataclasses.field(default_factory=list)
    out_avals: list[str] = dataclasses.field(default_factory=list)
    in_specs: list[str] = dataclasses.field(default_factory=list)
    out_specs: list[str] = dataclasses.field(default_factory=list)
    in_declared: list[str] = dataclasses.field(default_factory=list)
    findings: list[jc.Finding] = dataclasses.field(default_factory=list)
    error: str | None = None  # not analyzable / not mesh-bearing
    # live sharding objects (NOT committed to the ledger): the SC008
    # contract compares these semantically — a GSPMDSharding the partitioner
    # picked and the NamedSharding a producer declared stringify differently
    # but can be the same layout (Sharding.is_equivalent_to)
    in_shardings: list = dataclasses.field(default_factory=list, repr=False)
    out_shardings: list = dataclasses.field(default_factory=list, repr=False)
    in_ndims: list[int] = dataclasses.field(default_factory=list, repr=False)
    out_ndims: list[int] = dataclasses.field(default_factory=list, repr=False)

    @property
    def failing(self) -> list[jc.Finding]:
        return [f for f in self.findings if not f.suppressed]


def comms_fingerprint(
    parsed: dict, declared: list[Any], compiled_in: list[Any], in_avals: list[Any]
) -> dict:
    """The committed per-jit comms fingerprint: what the ledger stores and
    `check_comms_budget` gates. `wire_bytes` counts hot collectives times
    their known trip count (per dispatch of the whole jit)."""
    hist: dict[str, int] = {}
    hot_hist: dict[str, int] = {}
    wire = 0
    wire_hot = 0
    for c in parsed["collectives"]:
        hist[c.kind] = hist.get(c.kind, 0) + 1
        multiplier = (c.trip_count or 1) if c.hot else 1
        contrib = c.wire_bytes * multiplier
        wire += contrib
        if c.hot:
            hot_hist[c.kind] = hot_hist.get(c.kind, 0) + 1
            wire_hot += contrib
    floor = _replicated_floor()
    replicated_inputs: list[str] = []
    replicated_bytes = 0
    for i, (decl, comp, aval) in enumerate(zip(declared, compiled_in, in_avals)):
        if decl is not None:
            continue  # declared layouts are chosen, not silent
        if not getattr(comp, "is_fully_replicated", False):
            continue
        nbytes = _aval_bytes(aval)
        replicated_bytes += nbytes
        if nbytes >= floor:
            replicated_inputs.append(f"{i}:{jc._aval_str(aval)}")
    return {
        "num_partitions": int(parsed["num_partitions"]),
        "mesh": _mesh_axes(declared),
        "collectives": dict(sorted(hist.items())),
        "hot_collectives": dict(sorted(hot_hist.items())),
        "wire_bytes": int(wire),
        "wire_bytes_hot": int(wire_hot),
        "replicated_inputs": sorted(replicated_inputs),
        "replicated_bytes": int(replicated_bytes),
    }


def _fmt_bytes(n: int) -> str:
    if n >= 1 << 20:
        return f"{n / (1 << 20):.1f}MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f}KiB"
    return f"{n}B"


def analyze_entry(
    spec: str,
    entry: Any,
    rules: set[str] | None = None,
    force: bool = False,
) -> tuple[ShardReport, Any | None]:
    """Lower-and-compile one CompilePlan entry under its declared mesh and
    analyze the partitioned module (SC006/SC007 + the comms fingerprint).
    Entries whose example declares no multi-device sharding are skipped as
    not mesh-bearing unless `force` (edge endpoints are forced so SC008
    can compare both ends). Returns `(report, compiled)`."""
    from ..compile.plan import avals_of

    report = ShardReport(spec=spec, name=entry.name)
    fn, example = entry.fn, entry.example
    if example is None:
        report.error = "no example thunk (registered for timing only)"
        return report, None
    if not hasattr(fn, "trace") or not hasattr(fn, "lower"):
        report.error = "not traceable (wrapped callable without .trace/.lower)"
        return report, None
    try:
        specs = avals_of(example())
        declared = _declared_shardings(specs)
    except Exception as err:
        report.error = f"example failed: {type(err).__name__}: {err}"[:300]
        return report, None
    mesh_bearing = bool(_mesh_axes(declared))
    if not mesh_bearing and not force:
        report.error = "not mesh-bearing (no multi-device sharding declared)"
        return report, None
    try:
        traced = fn.trace(*specs)
        closed = traced.jaxpr
        compiled = traced.lower().compile()
    except Exception as err:
        report.error = f"lower/compile failed: {type(err).__name__}: {err}"[:300]
        return report, None

    in_avals = [v.aval for v in closed.jaxpr.invars]
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    compiled_in = _flat_input_shardings(compiled, len(in_avals))
    compiled_out = _flat_output_shardings(compiled, len(out_avals))
    report.in_avals = [jc._aval_str(a) for a in in_avals]
    report.out_avals = [jc._aval_str(a) for a in out_avals]
    report.in_specs = [spec_key(s) for s in compiled_in]
    report.out_specs = [spec_key(s) for s in compiled_out]
    report.in_declared = [spec_key(s) for s in declared]
    report.in_shardings = compiled_in
    report.out_shardings = compiled_out
    report.in_ndims = [len(getattr(a, "shape", ())) for a in in_avals]
    report.out_ndims = [len(getattr(a, "shape", ())) for a in out_avals]

    parsed = parse_hlo_comms(compiled.as_text())
    report.comms = comms_fingerprint(parsed, declared, compiled_in, in_avals)

    def emit(rule_id: str, message: str) -> None:
        if rules is not None and rule_id not in rules:
            return
        finding = jc.Finding(SHARD_RULES[rule_id], spec, entry.name, message)
        finding.suppressed = SHARD_SUPPRESSIONS.get((spec, entry.name, rule_id))
        report.findings.append(finding)

    for c in parsed["collectives"]:
        if c.hot:
            trip = f" x{c.trip_count} loop iterations" if c.trip_count else ""
            emit(
                "SC006",
                f"{c.kind} ({_fmt_bytes(c.wire_bytes)} on the wire per "
                f"dispatch{trip}) inside while/scan body `{c.name}` — "
                "per-step comms multiply by the trip count",
            )
    for item in report.comms["replicated_inputs"]:
        idx, aval = item.split(":", 1)
        emit(
            "SC007",
            f"input {idx} ({aval}) was left unspecified and the "
            f"partitioner fully replicated it over the "
            f"{report.comms['num_partitions']}-device mesh — "
            "silent replication (wasted HBM + replication traffic); "
            "commit it with an explicit sharding",
        )
    return report, compiled


# ---------------------------------------------------------------------------
# data-edge contracts (SC008)
# ---------------------------------------------------------------------------


def _same_layout(
    s_obj: Any, s_key: str, d_obj: Any, d_key: str, ndim: int
) -> bool:
    """Producer/consumer sharding equality: string keys first, then the
    semantic check — a GSPMDSharding the partitioner picked for an
    undeclared input and the NamedSharding the producer declared stringify
    differently but can be the identical layout."""
    if s_key == d_key:
        return True
    if (
        hasattr(s_obj, "is_equivalent_to")
        and hasattr(d_obj, "is_equivalent_to")
    ):
        try:
            return d_obj.is_equivalent_to(s_obj, ndim)
        except Exception:
            return False
    return False


def _auto_pairs(
    src_report: ShardReport, dst_report: ShardReport
) -> dict[str, tuple[list[str], list[str], list[str]]]:
    """Match producer outputs to consumer inputs by (shape, dtype) group.
    Positional pairing across two separately flattened pytrees is not
    recoverable in general, so the check is over aval groups — and only
    over the consumer inputs whose example DECLARED no layout: a declared
    sharding is a chosen contract (and the WarmJit aval check enforces it
    live), while an undeclared input's compiled sharding is whatever the
    partitioner picked — exactly where silent producer/consumer drift
    hides (and how tiny-width param shapes colliding with batch shapes
    stay out of the comparison). Returns aval -> (src_keys, dst_keys,
    unmatched_dst_keys): a group mismatches when some silent consumer
    sharding is layout-equal to NO producer sharding for that aval."""
    src_by_aval: dict[str, list[tuple[str, Any]]] = {}
    for i, (aval, sk) in enumerate(
        zip(src_report.out_avals, src_report.out_specs)
    ):
        obj = (
            src_report.out_shardings[i]
            if i < len(src_report.out_shardings) else None
        )
        src_by_aval.setdefault(aval.rstrip("~"), []).append((sk, obj))
    dst_by_aval: dict[str, list[tuple[str, Any, int]]] = {}
    for i, (aval, sk, declared) in enumerate(
        zip(dst_report.in_avals, dst_report.in_specs, dst_report.in_declared)
    ):
        if declared != "unspecified":
            continue  # declared layout: a chosen contract, not silent drift
        if sk in ("unused", "unspecified"):
            continue  # pruned by XLA / uninspectable: nothing to check
        obj = (
            dst_report.in_shardings[i]
            if i < len(dst_report.in_shardings) else None
        )
        ndim = dst_report.in_ndims[i] if i < len(dst_report.in_ndims) else 0
        dst_by_aval.setdefault(aval.rstrip("~"), []).append((sk, obj, ndim))
    out: dict[str, tuple[list[str], list[str], list[str]]] = {}
    for aval in sorted(set(src_by_aval) & set(dst_by_aval)):
        srcs = src_by_aval[aval]
        unmatched = sorted(
            {
                d_key
                for d_key, d_obj, ndim in dst_by_aval[aval]
                if not any(
                    _same_layout(s_obj, s_key, d_obj, d_key, ndim)
                    for s_key, s_obj in srcs
                )
            }
        )
        out[aval] = (
            sorted({sk for sk, _ in srcs}),
            sorted({dk for dk, _, _ in dst_by_aval[aval]}),
            unmatched,
        )
    return out


def resolve_edges(
    spec: str,
    edges: Iterable[Any],
    reports_by_name: dict[str, ShardReport],
    rules: set[str] | None = None,
) -> tuple[dict[str, dict], list[jc.Finding]]:
    """Resolve every declared DataEdge of one plan against the compiled
    shardings. Returns `(records, findings)`: records go to the ledger
    (keyed `src->dst`), SC008 findings fire on expect='match' mismatches."""
    records: dict[str, dict] = {}
    findings: list[jc.Finding] = []
    for edge in edges:
        src = reports_by_name.get(edge.src)
        dst = reports_by_name.get(edge.dst)
        rec: dict[str, Any] = {"expect": edge.expect}
        if edge.note:
            rec["note"] = edge.note
        if (
            src is None or dst is None
            or src.comms is None or dst.comms is None
        ):
            missing = edge.src if (src is None or src.comms is None) else edge.dst
            rec["status"] = "unresolved"
            rec["reason"] = f"{missing}: no compiled shardings"
            records[edge.key] = rec
            continue
        mismatched: dict[str, tuple[list[str], list[str]]] = {}
        contract: dict[str, dict] = {}
        if edge.pairs:
            for oi, ii in edge.pairs:
                try:
                    s_key, d_key = src.out_specs[oi], dst.in_specs[ii]
                    aval = src.out_avals[oi]
                except IndexError:
                    rec["status"] = "unresolved"
                    rec["reason"] = f"pair ({oi},{ii}) out of range"
                    break
                s_obj = (
                    src.out_shardings[oi]
                    if oi < len(src.out_shardings) else None
                )
                d_obj = (
                    dst.in_shardings[ii] if ii < len(dst.in_shardings) else None
                )
                ndim = dst.in_ndims[ii] if ii < len(dst.in_ndims) else 0
                contract[f"{aval}[{oi}->{ii}]"] = {"src": [s_key], "dst": [d_key]}
                if not _same_layout(s_obj, s_key, d_obj, d_key, ndim):
                    mismatched[f"{aval}[{oi}->{ii}]"] = ([s_key], [d_key])
            if rec.get("status") == "unresolved":
                records[edge.key] = rec
                continue
        else:
            for aval, (s_keys, d_keys, unmatched) in _auto_pairs(src, dst).items():
                contract[aval] = {"src": s_keys, "dst": d_keys}
                if unmatched:
                    mismatched[aval] = (s_keys, unmatched)
        rec["contract"] = contract
        rec["status"] = (
            "mismatch" if (mismatched and edge.expect == "match") else "ok"
        )
        records[edge.key] = rec
        if mismatched and edge.expect == "match":
            if rules is not None and "SC008" not in rules:
                continue
            detail = "; ".join(
                f"{aval}: {'/'.join(s)} -> {'/'.join(d)}"
                for aval, (s, d) in sorted(mismatched.items())
            )
            finding = jc.Finding(
                SHARD_RULES["SC008"],
                spec,
                edge.key,
                f"producer/consumer sharding contract broken on "
                f"{len(mismatched)} aval group(s): {detail} — every handoff "
                "pays an implicit reshard",
            )
            finding.suppressed = SHARD_SUPPRESSIONS.get(
                (spec, edge.key, "SC008")
            )
            findings.append(finding)
    return records, findings


def analyze_shard_plan(
    spec: str, plan: Any, rules: set[str] | None = None
) -> tuple[list[ShardReport], dict[str, dict], list[jc.Finding]]:
    """Analyze one captured CompilePlan: every mesh-bearing entry (plus
    edge endpoints) is compiled and fingerprinted, then the declared data
    edges are resolved. Returns `(reports, edge_records, edge_findings)`."""
    edges = plan.edges
    endpoint_names = {e.src for e in edges} | {e.dst for e in edges}
    reports: list[ShardReport] = []
    by_name: dict[str, ShardReport] = {}
    for entry in plan._entries:
        report, _compiled = analyze_entry(
            spec, entry, rules=rules, force=entry.name in endpoint_names
        )
        reports.append(report)
        by_name[entry.name] = report
    edge_records, edge_findings = resolve_edges(spec, edges, by_name, rules=rules)
    return reports, edge_records, edge_findings


# ---------------------------------------------------------------------------
# SC009: eager collectives in host loops (source-level, sheeplint engine)
# ---------------------------------------------------------------------------

_EAGER_COLLECTIVE_LEAVES = {
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter",
}
_MULTIHOST_LEAVES = {
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
}


def check_source_collectives(paths: Iterable[str]) -> list[jc.Finding]:
    """AST pass over `paths` for SC009: eager collective calls (jax.lax
    psum family, multihost_utils helpers) outside any jit context and
    lexically inside a Python loop. Suppressible with sheeplint's comment
    syntax (`# sheeplint: disable=SC009 — why`)."""
    from .linter import _FileAnalysis, _parse_suppressions, iter_python_files

    findings: list[jc.Finding] = []
    for path in iter_python_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            a = _FileAnalysis(src, path)
        except (OSError, SyntaxError, ValueError):
            continue
        per_line, file_level = _parse_suppressions(src)
        if "all" in file_level or "SC009" in file_level:
            continue
        for node in ast.walk(a.tree):
            if not isinstance(node, ast.Call):
                continue
            d = a._dotted(node.func)
            if d is None:
                continue
            root, _, leaf = d.rpartition(".")
            root_head = root.split(".", 1)[0]
            is_collective = (
                leaf in _EAGER_COLLECTIVE_LEAVES
                and (root_head in ("jax", "lax") or ".lax" in root)
            ) or (leaf in _MULTIHOST_LEAVES and "multihost" in d)
            if not is_collective or a._in_jit_context(node):
                continue
            in_loop = False
            for p in a._parents(node):
                if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    break
                if isinstance(p, (ast.For, ast.While)):
                    in_loop = True
                    break
            if not in_loop:
                continue
            line = getattr(node, "lineno", 1)
            sup = per_line.get(line, set())
            if "all" in sup or "SC009" in sup:
                continue
            findings.append(
                jc.Finding(
                    SHARD_RULES["SC009"],
                    "<source>",
                    f"{path}:{line}",
                    f"eager `{d}` inside an un-jitted host loop — one "
                    "single-collective dispatch per iteration",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# the sweep: mesh-bearing capture configurations
# ---------------------------------------------------------------------------

# spec -> (sheepcheck capture spec, extra argv APPENDED after it — later
# flags win). These define the mesh each comms fingerprint is derived
# under; they are part of the committed ledger's contract the same way
# CAPTURE_ARGV is for the compile-cost fingerprints. The virtual 8-mesh
# matches the tests/conftest + CI harness.
SHARD_SWEEP: dict[str, tuple[str, list[str]]] = {
    # data-parallel PPO on the full virtual 8-mesh: the per-minibatch
    # gradient all-reduce inside the epoch/minibatch scan
    "ppo@mesh8": ("ppo", ["--num_devices", "8", "--num_envs", "8"]),
    # the Anakin arrangement on the 8-mesh: env batch sharded over the
    # mesh, zero collectives inside the rollout scan by design, plus the
    # rollout->gae->train_step data edges
    "ppo@anakin": ("ppo@anakin", ["--num_devices", "8", "--num_envs", "8"]),
    # context parallelism: (data=4, seq=2) mesh — the seq-axis boundary
    # all-gathers around the RSSM scan. --train_every 8 keeps the dry-run
    # sequence clamp at the full T=8 window (the clamp floors T at
    # train_every/num_envs, and T=1 cannot shard over the seq axis).
    "dreamer_v3@seq": (
        "dreamer_v3",
        [
            "--num_devices", "8", "--seq_devices", "2",
            "--per_rank_batch_size", "4", "--train_every", "8",
        ],
    ),
    # Anakin Dreamer: sharded collectors + the device replay ring
    "dreamer_v3@anakin": ("dreamer_v3@anakin", ["--num_devices", "2", "--num_envs", "2"]),
    # decoupled player/trainer topologies: 1 player device + trainer mesh
    "ppo_decoupled@mesh": ("ppo_decoupled", ["--num_devices", "5"]),
    "sac_decoupled@mesh": ("sac_decoupled", ["--num_devices", "5"]),
    "dreamer_v3_decoupled@mesh": ("dreamer_v3_decoupled", ["--num_devices", "3"]),
}


def resolve_capture(spec: str) -> tuple[str, list[str]]:
    """Map a sheepshard sweep spec to `(algo, extra_argv)` for
    `jaxpr_check.capture_plan` — the sheepcheck capture/variant argv with
    the mesh overrides appended."""
    if spec in SHARD_SWEEP:
        base_spec, extra = SHARD_SWEEP[spec]
        algo, variant_argv = jc.resolve_capture(base_spec)
        return algo, [*variant_argv, *extra]
    return jc.resolve_capture(spec)


# ---------------------------------------------------------------------------
# comms ledger: build + drift gate
# ---------------------------------------------------------------------------


def build_comms_budget(
    reports: list[ShardReport],
    edges_by_spec: dict[str, dict[str, dict]],
    wire_bytes_frac: float = 0.25,
) -> dict:
    import jax

    return {
        "version": 1,
        "jax_version": jax.__version__,
        "tolerance": {"wire_bytes_frac": wire_bytes_frac},
        "comms": {
            f"{r.spec}/{r.name}": r.comms for r in reports if r.comms is not None
        },
        "edges": {
            f"{spec}/{key}": rec
            for spec, recs in sorted(edges_by_spec.items())
            for key, rec in sorted(recs.items())
        },
    }


def check_comms_budget(ledger: dict, derived: dict) -> tuple[list[str], list[str]]:
    """The CI comms drift gate. Failures are the ISSUE-gated classes: a
    new collective kind, a new or multiplied hot-loop collective,
    wire-bytes growth past tolerance, a newly replicated large tensor, a
    match-edge resolving to mismatch, and added/removed ledger entries.
    Reductions and contract improvements are notes."""
    failures: list[str] = []
    notes: list[str] = []
    tol = float(ledger.get("tolerance", {}).get("wire_bytes_frac", 0.25))
    old, new = ledger.get("comms", {}), derived.get("comms", {})
    for key in sorted(set(old) - set(new)):
        failures.append(f"{key}: comms fingerprint disappeared (ledger has it)")
    for key in sorted(set(new) - set(old)):
        failures.append(f"{key}: new mesh-bearing jit not in the comms ledger")
    for key in sorted(set(old) & set(new)):
        o, n = old[key], new[key]
        o_hist, n_hist = o.get("collectives", {}), n.get("collectives", {})
        new_kinds = sorted(set(n_hist) - set(o_hist))
        if new_kinds:
            failures.append(f"{key}: new collective kind(s) {new_kinds}")
        lost_kinds = sorted(set(o_hist) - set(n_hist))
        if lost_kinds:
            notes.append(f"{key}: collective kind(s) {lost_kinds} eliminated")
        o_hot, n_hot = o.get("hot_collectives", {}), n.get("hot_collectives", {})
        for kind in sorted(set(n_hot)):
            if n_hot[kind] > o_hot.get(kind, 0):
                failures.append(
                    f"{key}: hot-loop {kind} count grew "
                    f"{o_hot.get(kind, 0)} -> {n_hot[kind]} (collectives "
                    "inside while/scan bodies multiply per-step comms)"
                )
        for kind in sorted(set(o_hot)):
            if o_hot[kind] > n_hot.get(kind, 0):
                notes.append(
                    f"{key}: hot-loop {kind} count shrank "
                    f"{o_hot[kind]} -> {n_hot.get(kind, 0)}"
                )
        ow, nw = int(o.get("wire_bytes", 0)), int(n.get("wire_bytes", 0))
        if nw > ow * (1.0 + tol) and nw - ow > 1024:
            failures.append(
                f"{key}: comms bytes grew {ow} -> {nw} "
                f"(+{(nw - ow) / max(ow, 1):.0%}, tolerance {tol:.0%})"
            )
        elif nw < ow * (1.0 - tol) and ow - nw > 1024:
            notes.append(
                f"{key}: comms bytes shrank {ow} -> {nw} — refresh the ledger"
            )
        newly_replicated = sorted(
            set(n.get("replicated_inputs", [])) - set(o.get("replicated_inputs", []))
        )
        if newly_replicated:
            failures.append(
                f"{key}: newly replicated large tensor(s) {newly_replicated} "
                "— silent full replication under the sharded mesh"
            )
        dereplicated = sorted(
            set(o.get("replicated_inputs", [])) - set(n.get("replicated_inputs", []))
        )
        if dereplicated:
            notes.append(f"{key}: tensor(s) no longer replicated {dereplicated}")
    o_edges, n_edges = ledger.get("edges", {}), derived.get("edges", {})
    for key in sorted(set(o_edges) - set(n_edges)):
        failures.append(f"{key}: data edge disappeared (ledger has it)")
    for key in sorted(set(n_edges) - set(o_edges)):
        if n_edges[key].get("status") == "mismatch":
            failures.append(f"{key}: new data edge resolves to a sharding mismatch")
        else:
            failures.append(f"{key}: new data edge not in the ledger")
    for key in sorted(set(o_edges) & set(n_edges)):
        o_st, n_st = o_edges[key].get("status"), n_edges[key].get("status")
        if o_st == n_st:
            continue
        if n_st == "mismatch":
            failures.append(
                f"{key}: sharding contract broke ({o_st} -> mismatch) — "
                "every handoff now pays an implicit reshard"
            )
        else:
            notes.append(f"{key}: edge status changed {o_st} -> {n_st}")
    return failures, notes
