"""The sheeplint rule catalog.

Every rule names a *statically detectable* JAX/TPU hazard class this codebase
has either been bitten by (SL001 is the PR-1 heap-corruption class, SL004 is
the 951-second compile-probe class) or that podracer-style TPU stacks
(arXiv:2104.06272) treat as a hot-loop invariant: no host↔device syncs, no
Python control flow on tracers, no per-step recompiles. Rules carry an id,
severity, one-line summary, and an autofix hint printed with each finding.

Suppression: append `# sheeplint: disable=SL002` to the offending line (or
put the comment alone on the line above), `disable=all` for every rule, or a
file-level `# sheeplint: disable-file=SL003` anywhere in the file. Every
suppression in this repo must carry a justification in the same comment —
the self-lint test keeps the repo at zero unsuppressed findings.
"""

from __future__ import annotations

import dataclasses

__all__ = ["Rule", "Violation", "RULES", "rule_ids"]

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    severity: str
    summary: str
    autofix: str


@dataclasses.dataclass
class Violation:
    rule: Rule
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule.id} "
            f"[{self.rule.severity}] {self.message} (fix: {self.rule.autofix})"
        )

    def as_dict(self) -> dict:
        return {
            "rule": self.rule.id,
            "severity": self.rule.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "autofix": self.rule.autofix,
        }


_RULES = [
    Rule(
        id="SL001",
        name="bare-donating-jit",
        severity=ERROR,
        summary=(
            "bare jax.jit(..., donate_argnums=...) outside utils/jit."
            "donating_jit — deserialized donating executables corrupt the "
            "glibc heap on XLA:CPU with the persistent cache (PR 1)"
        ),
        autofix=(
            "use sheeprl_tpu.utils.jit.donating_jit (same signature); keep "
            "raw donation only for sub-cache-floor compiles, with a "
            "justified suppression"
        ),
    ),
    Rule(
        id="SL002",
        name="host-sync-in-jit",
        severity=ERROR,
        summary=(
            "host-sync call (.item()/.tolist()/float()/int()/bool()/"
            "np.asarray/device_get/block_until_ready) on a traced value "
            "inside a jit/scan/vmap body — forces a device round-trip per "
            "trace and breaks the single-dispatch hot loop"
        ),
        autofix=(
            "keep the value on device (jnp ops), or move the sync outside "
            "the traced function; for debugging use jax.debug.print"
        ),
    ),
    Rule(
        id="SL003",
        name="python-branch-on-tracer",
        severity=ERROR,
        summary=(
            "Python if/while on a traced array inside a jit/scan/vmap body "
            "— raises TracerBoolConversionError or silently bakes one "
            "branch at trace time"
        ),
        autofix=(
            "use jax.lax.cond / lax.select / lax.while_loop, or "
            "checkify for error branches"
        ),
    ),
    Rule(
        id="SL004",
        name="recompile-hazard",
        severity=WARNING,
        summary=(
            "recompile hazard: jit closure built inside a per-step loop, or "
            "static_argnums over an unhashable (mutable-default) parameter "
            "— every call site pays a fresh XLA trace/compile"
        ),
        autofix=(
            "hoist the jit out of the loop (build once, call per step) and "
            "make static args hashable (tuples, not lists)"
        ),
    ),
    Rule(
        id="SL005",
        name="unregistered-dataclass-pytree",
        severity=ERROR,
        summary=(
            "@dataclass used inside jitted code without jax.tree_util "
            "registration — leaves are invisible to tracing/grad and the "
            "instance is retraced as a static constant"
        ),
        autofix=(
            "register with jax.tree_util.register_dataclass / "
            "register_pytree_node_class, or subclass sheeprl_tpu.nn.Module "
            "(auto-registers)"
        ),
    ),
    Rule(
        id="SL006",
        name="unconstrained-sharded-jit",
        severity=WARNING,
        summary=(
            "jitted function in parallel/ builds shardings but never "
            "applies with_sharding_constraint — GSPMD is free to gather "
            "the array onto one device inside the jit"
        ),
        autofix=(
            "pin layouts with jax.lax.with_sharding_constraint (or the "
            "mesh.make_constrain helper) at the function's phase boundaries"
        ),
    ),
    Rule(
        id="SL007",
        name="host-sync-in-hot-loop",
        severity=WARNING,
        summary=(
            "blocking host sync (.item()/.tolist()/float()/int()/bool()/"
            "np.asarray/jax.device_get/block_until_ready) inside a "
            "hot-loop body (a function named one_cycle/one_step/"
            "one_update/*hot_loop*, or marked `# sheeplint: hotloop`) — "
            "the pull serializes the critical path the pipeline "
            "primitives exist to overlap"
        ),
        autofix=(
            "route the pull through sheeprl_tpu.parallel.pipeline "
            "(ActionPipeline dispatch/get, SamplePrefetcher, MetricDrain) "
            "or move it off the hot loop; intentional sync barriers "
            "(timing fences) get a justified suppression"
        ),
    ),
    Rule(
        id="SL008",
        name="host-callback-in-hotloop-scan",
        severity=ERROR,
        summary=(
            "host callback (jax.debug.print / jax.debug.callback / "
            "io_callback / pure_callback / host_callback) traced into a "
            "hot-loop scan/jit body (a body named one_cycle/one_step/"
            "one_update/*hot_loop* or marked `# sheeplint: hotloop`) — "
            "each scan iteration pays a device->host round-trip, "
            "serializing the fully-jitted rollout the Anakin path exists "
            "for (sheepcheck SC002 is the IR-level twin over every "
            "registered jit)"
        ),
        autofix=(
            "drop the callback from the hot body (aggregate on device and "
            "pull once per rollout), or keep it behind a debug flag with "
            "a justified suppression"
        ),
    ),
    Rule(
        id="SL009",
        name="weak-constant-to-jit",
        severity=WARNING,
        summary=(
            "bare Python numeric constant passed to a jit-bound callable "
            "(a name assigned from jax.jit/donating_jit/plan.register) — "
            "the scalar enters as a weak-typed 0-d array, so mixing the "
            "call with strong-typed call sites retraces the whole jit, "
            "and every call pays an implicit host->device put (the PR-2 "
            "gamma/lambda class)"
        ),
        autofix=(
            "wrap the constant once outside the loop: jnp.float32(x) / "
            "jnp.asarray(x, dtype) — a committed device scalar with a "
            "strong dtype"
        ),
    ),
    Rule(
        id="SL010",
        name="unsharded-batch-put",
        severity=WARNING,
        summary=(
            "jax.device_put / jnp.asarray of a batch-sized array (a "
            "replay-buffer read or batch/sample/rollout-named value) "
            "inside a mesh-building function without an explicit sharding "
            "— the put lands uncommitted on the default device, so sharded "
            "consumers silently replicate or single-device the batch (the "
            "host-side twin of sheepshard SC007)"
        ),
        autofix=(
            "route the put through shard_batch / shard_time_batch / "
            "shard_env_batch (or device_put with a NamedSharding); where "
            "the unsharded put IS the design (player-side data, an "
            "explicit reshard downstream), suppress with the justification"
        ),
    ),
    Rule(
        id="SL011",
        name="ndarray-constant-closure",
        severity=WARNING,
        summary=(
            "jit-wrapped function closes over a module-level/global "
            "ndarray constant (a name assigned at module scope from "
            "np.*/jnp.* array constructors) — the array is baked into "
            "EVERY compiled executable as an embedded constant: it bloats "
            "each persistent-cache entry, re-materializes per executable, "
            "and can never be donated or sharded (sheepmem SC012 is the "
            "compiled-level twin that measures the bytes)"
        ),
        autofix=(
            "pass the array as a jit argument (one shared device buffer "
            "across executables), construct it inside the jit from "
            "iota/broadcast, or suppress with a justification for small "
            "lookup tables"
        ),
    ),
    Rule(
        id="SL012",
        name="swallowed-exception",
        severity=WARNING,
        summary=(
            "broad exception handler (bare except / except Exception / "
            "BaseException) whose body only passes/continues — the failure "
            "is swallowed with NO log, NO telemetry event and NO re-raise. "
            "In an algo main or hot-loop helper this is the silent-failure "
            "class the resilience subsystem (ISSUE 12) exists to kill: a "
            "crashed env, a failed checkpoint or a dead transfer degrades "
            "the run with zero forensic trail"
        ),
        autofix=(
            "narrow the exception type, or handle it visibly: re-raise, "
            "telemetry.emit an event, bump a Fault/* counter, or log; "
            "a genuinely-safe swallow (best-effort close of an already-"
            "crashed resource) gets a justified suppression"
        ),
    ),
    Rule(
        id="SL013",
        name="device-array-to-wire",
        severity=ERROR,
        summary=(
            "a device value (a name assigned from a jax.*/jnp.* call) "
            "reaches a serialization/socket sink (.tobytes(), "
            "send/sendall/sendto/send_bytes, pickle.dump/dumps) without an "
            "explicit host pull — the byte view forces a hidden blocking "
            "d2h transfer at the sink (and .tobytes() on a sharded array "
            "gathers it whole), so the transfer cost is invisible to the "
            "phase timers and the flock hot path (ISSUE 14: every byte "
            "that crosses a socket must be pulled host-side exactly once, "
            "where the telemetry can see it)"
        ),
        autofix=(
            "pull explicitly first: np.asarray(x) / np.ascontiguousarray(x) "
            "/ jax.device_get(x) — then serialize the host array (the "
            "data/wire.py pack_* helpers already do this); an intentional "
            "device-buffer send gets a justified suppression"
        ),
    ),
    Rule(
        id="SL014",
        name="anonymous-thread",
        severity=WARNING,
        summary=(
            "threading.Thread constructed without an explicit `name=` or "
            "without an explicit `daemon=` decision (or a threading.Timer "
            "whose stored handle never gets a `.daemon =` assignment). An "
            "unnamed thread breaks sheeptrace/sheepsync role attribution — "
            "every telemetry event, lock acquisition and violation record "
            "is keyed by thread name — and an implicit daemon flag "
            "inherits from the spawner, so whether the thread can block "
            "interpreter shutdown is an accident of call site (ISSUE 18: "
            "the thread inventory in the concurrency ledger needs both)"
        ),
        autofix=(
            "pass name='<role>-<purpose>' and an explicit daemon=True/"
            "False to the constructor; for Timer (no daemon kwarg) set "
            "`t.daemon = True` on the stored handle before start(); "
            "Thread subclasses decide both in their own __init__"
        ),
    ),
]

RULES: dict[str, Rule] = {r.id: r for r in _RULES}


def rule_ids() -> list[str]:
    return sorted(RULES)
