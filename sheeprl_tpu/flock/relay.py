"""Hierarchical actor aggregation: the relay tier (ISSUE 19, tentpole c).

`python -m sheeprl_tpu.flock.relay` hosts one relay between a group of
actors and the learner's replay service, so the learner holds O(relays)
connections instead of O(actors) — the Sebulba scale-out shape
(arXiv:2104.06272). Downstream, a relay speaks the EXACT service
protocol actors already use (HELLO/WELCOME, PUSH/PUSH_OK,
HEARTBEAT/HEARTBEAT_OK, GET_WEIGHTS, SHM_ATTACH, BYE) — `ActorFleet`
just hands actors a relay address and zero actor code changes follow.
Upstream, everything multiplexes over ONE connection:

    RELAY_HELLO  opens it (reply WELCOME {shard_capacity,
                 weight_version, random_phase})
    PUSH_BATCH   batches buffered PUSH payloads, forwarded VERBATIM —
                 shard bytes and sheepscope trace context survive the
                 hop bit-for-bit; one aggregate PUSH_OK refreshes the
                 relay's cached reply fields
    RELAY_FWD    wraps actor control frames (HELLO/HEARTBEAT/BYE) so
                 learner-side membership, generation bumps and
                 `flock.actor_rejoined` receipts fire exactly as if the
                 actor were directly connected

Pushes are acknowledged downstream IMMEDIATELY from cached state and
flushed upstream by a forwarder thread (`flock-relay-fwd`), so an
actor's push latency is one local hop regardless of learner load.
Weight pulls are served from a single cached snapshot per version: a
poller thread (`flock-relay-weights`) keeps the newest WEIGHTS payload
(raw frame bytes, reused verbatim for every downstream GET_WEIGHTS), so
N actors cost the learner ONE weight transfer per published version.

Elasticity: a dying upstream connection is redialed with the actor-side
backoff budget, and every known member re-HELLOs through the fresh
connection (the service had deregistered them with the dead relay — the
re-registration bumps generations, exactly the rejoin path). A relay
killed outright is respawned by `ActorFleet` at the SAME bind address,
and its actors' `ResilientLink` reconnects ride through. Colocated
actors may SHM_ATTACH to the relay: the ring drains into the relay's
upstream batch queue through the same `flock/shm.py` receiver the
service uses.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Any

from ..telemetry import core as telemetry
from . import wire
from .service import PROTO_VERSION

__all__ = ["Relay"]

_U32 = __import__("struct").Struct("<I")

BATCH_MAX = 8  # pushes per PUSH_BATCH frame
FLUSH_S = 0.02  # max dwell of a buffered push before a forced flush
QUEUE_CAP = 256  # buffered pushes across all members; oldest dropped past it
WEIGHT_POLL_S = 0.25


class Relay:
    """One actor->learner aggregation hop; see the module docstring."""

    def __init__(
        self,
        *,
        upstream: str,
        relay_id: int,
        bind: str | None = None,
        telem=None,
    ):
        self.upstream = upstream
        self.relay_id = relay_id
        self._requested_bind = bind
        self._telem = telem
        self.address = ""
        self._listener: socket.socket | None = None
        self._unix_path: str | None = None
        self._own_sockdir = False
        # guards members/cache/queue/counters. NEVER taken around upstream
        # socket I/O — that is `_up_lock`'s job, and `_up_lock` is never
        # acquired while `_lock` is held (sheepsync lock-order ledger).
        self._lock = threading.Lock()
        self._queue_ready = threading.Condition(self._lock)
        self._queue: deque[tuple[int, bytes]] = deque()
        self._dropped = 0
        self._members: dict[int, dict] = {}  # actor_id -> last hello
        self._cache: dict[str, Any] = {
            "rows_total": 0,
            "random_phase": False,
            "weight_version": 0,
        }
        self._weight_payload: bytes | None = None
        self._weight_version = -1
        self._shm_rx: dict[int, Any] = {}
        # serializes request/reply traffic on the one upstream connection
        self._up_lock = threading.Lock()
        self._up_sock: socket.socket | None = None
        self._stop = threading.Event()
        self.fatal = threading.Event()  # upstream unreachable past budget
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._batches = 0
        self._forwarded = 0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> str:
        sock = self._dial_upstream()  # fail fast: no learner, no relay
        with self._up_lock:  # every _up_sock write happens under _up_lock
            self._up_sock = sock
        if self._requested_bind:
            parsed = wire.parse_address(self._requested_bind)
            if parsed[0] == "tcp":
                srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                srv.bind((parsed[1], parsed[2]))
            else:
                # a respawned relay rebinds its predecessor's path so the
                # actors' reconnect backoff finds it (service rehost logic)
                path = parsed[1]
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                try:
                    os.unlink(path)
                except OSError:
                    pass
                self._unix_path = path
                srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                srv.bind(path)
            self.address = self._requested_bind
        else:
            sock_dir = tempfile.mkdtemp(prefix="flock-relay-")
            self._own_sockdir = True
            self._unix_path = os.path.join(sock_dir, "relay.sock")
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(self._unix_path)
            self.address = wire.format_address("unix", self._unix_path)
        srv.listen(64)
        self._listener = srv
        for target, name in (
            (self._accept_loop, "flock-relay-accept"),
            (self._forward_loop, "flock-relay-fwd"),
            (self._weight_loop, "flock-relay-weights"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        self._event(
            "flock.relay_started",
            relay_id=self.relay_id,
            address=self.address,
            upstream=self.upstream,
        )
        return self.address

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            self._queue_ready.notify_all()
            receivers = list(self._shm_rx.values())
            self._shm_rx.clear()
        for rx in receivers:
            rx.stop(unlink=True)
        for sock in [self._listener, self._up_sock, *self._conns]:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        for t in self._threads:
            t.join(timeout=2.0)
        if self._unix_path:
            try:
                os.unlink(self._unix_path)
                if self._own_sockdir:
                    os.rmdir(os.path.dirname(self._unix_path))
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- upstream -------------------------------------------------------------

    def _dial_upstream(self) -> socket.socket:
        """(Re)open the multiplexed upstream connection with the actor-side
        backoff budget, then re-HELLO every known member through it — the
        service deregistered them when the previous connection died, so the
        re-registration is exactly the rejoin path. Returns the socket;
        the CALLER stores it into `_up_sock` under `_up_lock` (start()
        dials before taking the lock, `_up_request` already holds it)."""
        from .actor import BACKOFF_BASE_S, BACKOFF_CAP_S, _reconnect_budget

        budget = _reconnect_budget()
        deadline = time.monotonic() + budget
        delay = BACKOFF_BASE_S
        while True:
            try:
                sock = wire.connect(self.upstream, timeout=30.0)
                wire.send_json(
                    sock,
                    wire.RELAY_HELLO,
                    {
                        "relay_id": self.relay_id,
                        "pid": os.getpid(),
                        "proto": PROTO_VERSION,
                    },
                )
                welcome = wire.recv_json(sock, wire.WELCOME)
                with self._lock:
                    self._cache["random_phase"] = bool(
                        welcome.get("random_phase")
                    )
                    self._cache["weight_version"] = int(
                        welcome.get("weight_version", 0)
                    )
                    members = dict(self._members)
                for aid, hello in members.items():
                    wire.send_frame(
                        sock,
                        wire.RELAY_FWD,
                        wire.pack_relay_fwd(
                            aid, wire.HELLO, json.dumps(hello).encode()
                        ),
                    )
                    wire.recv_frame(sock)  # RELAY_FWD(WELCOME): drain it
                return sock
            except (OSError, TimeoutError, wire.FrameError) as err:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    self.fatal.set()
                    raise ConnectionError(
                        f"flock upstream {self.upstream!r} unreachable after "
                        f"{budget:.0f}s (last: {type(err).__name__}: {err})"
                    ) from err
                time.sleep(min(delay, left))
                delay = min(delay * 2.0, BACKOFF_CAP_S)

    def _up_request(
        self, kind: int, payload: bytes, idempotent: bool = True
    ) -> tuple[int, bytes]:
        """One request/reply on the upstream connection; redials once on a
        dead socket. Idempotent frames (HELLO/HEARTBEAT/BYE forwards — the
        service coalesces re-registration) are replayed on the fresh
        connection. Non-idempotent ones (PUSH_BATCH: rows would land twice)
        are replayed ONLY if the failure happened before the send completed
        — once the bytes may have reached the service, a retry is a
        duplicate, so the caller gets the error instead."""
        with self._up_lock:
            for attempt in (0, 1):
                sock = self._up_sock
                sent = False
                try:
                    if sock is None:
                        sock = self._dial_upstream()
                        self._up_sock = sock
                    wire.send_frame(sock, kind, payload)
                    sent = True
                    frame = wire.recv_frame(sock)
                    if frame is None:
                        raise ConnectionResetError("upstream closed")
                    return frame
                except (OSError, TimeoutError, wire.FrameError):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    self._up_sock = None
                    if attempt or self._stop.is_set():
                        raise
                    if sent and not idempotent:
                        raise
        raise ConnectionError("unreachable")  # pragma: no cover

    # -- forwarder ------------------------------------------------------------

    def _enqueue(self, actor_id: int, payload: bytes) -> None:
        with self._lock:
            if len(self._queue) >= QUEUE_CAP:
                self._queue.popleft()
                self._dropped += 1
            self._queue.append((actor_id, payload))
            self._queue_ready.notify_all()

    def _forward_loop(self) -> None:
        """Drain the push queue into PUSH_BATCH frames: up to BATCH_MAX
        payloads per frame, flushed within FLUSH_S of the first buffered
        push. The aggregate PUSH_OK refreshes the cached reply fields every
        downstream PUSH is answered from."""
        while not self._stop.is_set():
            with self._queue_ready:
                # SY005: predicate re-checked in the loop head
                while not self._queue and not self._stop.is_set():
                    self._queue_ready.wait(timeout=0.5)
                if self._stop.is_set() and not self._queue:
                    return
                batch = []
                while self._queue and len(batch) < BATCH_MAX:
                    batch.append(self._queue.popleft())
            if not batch and not self._queue:
                continue
            try:
                kind, reply = self._up_request(
                    wire.PUSH_BATCH, wire.pack_push_batch(batch),
                    idempotent=False,
                )
            except (ConnectionError, TimeoutError, wire.FrameError):
                if self.fatal.is_set():
                    return
                continue  # batch lost with the connection; actors re-push
            if kind == wire.PUSH_OK:
                ok = json.loads(reply.decode())
                with self._lock:
                    self._cache.update(
                        rows_total=int(ok.get("rows_total", 0)),
                        random_phase=bool(ok.get("random_phase")),
                        weight_version=int(ok.get("weight_version", 0)),
                    )
                    self._batches += 1
                    self._forwarded += len(batch)
            # small dwell so near-simultaneous pushes share one batch
            self._stop.wait(FLUSH_S)

    # -- weight cache ---------------------------------------------------------

    def _weight_loop(self) -> None:
        """Dedicated upstream weights connection (HELLO actor_id=-1): keeps
        ONE cached WEIGHTS payload — the newest version — reused verbatim
        for every downstream GET_WEIGHTS."""
        sock = None
        while not self._stop.is_set():
            try:
                if sock is None:
                    sock = wire.connect(self.upstream, timeout=30.0)
                    wire.send_json(
                        sock,
                        wire.HELLO,
                        {
                            "actor_id": -1,
                            "pid": os.getpid(),
                            "role": "weights",
                            "proto": PROTO_VERSION,
                        },
                    )
                wire.send_json(
                    sock,
                    wire.GET_WEIGHTS,
                    {"have_version": self._weight_version},
                )
                frame = wire.recv_frame(sock)
                if frame is None:
                    raise ConnectionResetError("upstream weights closed")
                kind, payload = frame
                if kind == wire.WEIGHTS:
                    (meta_len,) = _U32.unpack_from(payload, 0)
                    meta = json.loads(payload[4 : 4 + meta_len].decode())
                    with self._lock:
                        self._weight_version = int(meta["version"])
                        self._weight_payload = payload
            except (OSError, wire.FrameError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
            self._stop.wait(WEIGHT_POLL_S)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    # -- downstream -----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(
                target=self._serve,
                args=(conn,),
                name="flock-relay-conn",
                daemon=True,
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        actor_id = None
        role = "data"
        try:
            frame = wire.recv_frame(conn)
            if frame is None:
                return
            if frame[0] == wire.PROFILE:
                from ..telemetry.trace import handle_profile_frame

                log_dir = getattr(self._telem, "log_dir", None)
                wire.send_json(
                    conn,
                    wire.PROFILE,
                    handle_profile_frame(
                        json.loads(frame[1].decode() or "{}"), log_dir
                    ),
                )
                return
            if frame[0] != wire.HELLO:
                return
            hello = json.loads(frame[1].decode())
            actor_id = int(hello["actor_id"])
            role = hello.get("role", "data")
            if hello.get("proto") != PROTO_VERSION:
                wire.send_json(
                    conn, wire.ERROR, {"error": f"bad hello {hello!r}"}
                )
                return
            if role == "weights":
                self._serve_weights(conn)
                return
            # forward the HELLO: the learner registers the actor (and bumps
            # its generation on rejoin) exactly as with a direct connection
            kind, reply = self._up_request(
                wire.RELAY_FWD,
                wire.pack_relay_fwd(
                    actor_id, wire.HELLO, json.dumps(hello).encode()
                ),
            )
            if kind != wire.RELAY_FWD:
                wire.send_json(
                    conn, wire.ERROR, {"error": "relay upstream refused hello"}
                )
                return
            _aid, inner_kind, inner = wire.unpack_relay_fwd(reply)
            if inner_kind != wire.WELCOME:
                wire.send_frame(conn, inner_kind, inner)
                return
            with self._lock:
                self._members[actor_id] = hello
            wire.send_frame(conn, wire.WELCOME, inner)
            while not self._stop.is_set():
                frame = wire.recv_frame(conn)
                if frame is None:
                    break
                kind, payload = frame
                if kind == wire.PUSH:
                    self._enqueue(actor_id, payload)
                    with self._lock:
                        ok = dict(self._cache)
                    wire.send_json(conn, wire.PUSH_OK, ok)
                elif kind == wire.HEARTBEAT:
                    self._handle_heartbeat(conn, actor_id, payload)
                elif kind == wire.SHM_ATTACH:
                    self._handle_shm_attach(
                        conn, actor_id, json.loads(payload.decode())
                    )
                elif kind == wire.BYE:
                    with self._lock:
                        self._members.pop(actor_id, None)
                    try:
                        self._up_request(
                            wire.RELAY_FWD,
                            wire.pack_relay_fwd(actor_id, wire.BYE, payload),
                        )
                    except (ConnectionError, TimeoutError, wire.FrameError):
                        pass
                    break
                else:
                    wire.send_json(
                        conn,
                        wire.ERROR,
                        {"error": f"unexpected {wire.KIND_NAMES.get(kind, kind)}"},
                    )
        except (wire.FrameError, OSError, ValueError, KeyError) as err:
            if not self._stop.is_set():
                self._event(
                    "flock.relay_conn_error",
                    relay_id=self.relay_id,
                    actor_id=actor_id,
                    role=role,
                    error=f"{type(err).__name__}: {err}",
                )
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if actor_id is not None and role == "data":
                with self._lock:
                    rx = self._shm_rx.pop(actor_id, None)
                if rx is not None:
                    rx.stop(unlink=True)

    def _serve_weights(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            frame = wire.recv_frame(conn)
            if frame is None:
                return
            kind, payload = frame
            if kind != wire.GET_WEIGHTS:
                wire.send_json(conn, wire.ERROR, {"error": "weights conn"})
                return
            have = json.loads(payload.decode()).get("have_version", -1)
            with self._lock:
                version = self._weight_version
                blob = self._weight_payload
            if blob is None or have == version:
                wire.send_json(
                    conn, wire.WEIGHTS_UNCHANGED, {"version": max(version, 0)}
                )
            else:
                wire.send_frame(conn, wire.WEIGHTS, blob)

    def _handle_heartbeat(self, conn, actor_id: int, payload: bytes) -> None:
        """Forward the heartbeat synchronously (1 Hz per actor — cheap) so
        learner-side staleness/eviction sees real liveness; when the
        upstream is down mid-redial, answer from cache so the ACTOR's link
        stays healthy while the relay heals."""
        try:
            kind, reply = self._up_request(
                wire.RELAY_FWD,
                wire.pack_relay_fwd(actor_id, wire.HEARTBEAT, payload),
            )
            if kind == wire.RELAY_FWD:
                _aid, inner_kind, inner = wire.unpack_relay_fwd(reply)
                if inner_kind == wire.HEARTBEAT_OK:
                    ok = json.loads(inner.decode())
                    with self._lock:
                        self._cache.update(
                            random_phase=bool(ok.get("random_phase")),
                            weight_version=int(ok.get("weight_version", 0)),
                        )
                    wire.send_frame(conn, wire.HEARTBEAT_OK, inner)
                    return
        except (ConnectionError, TimeoutError, wire.FrameError):
            if self.fatal.is_set():
                raise
        with self._lock:
            ok = {
                "random_phase": self._cache["random_phase"],
                "weight_version": self._cache["weight_version"],
            }
        wire.send_json(conn, wire.HEARTBEAT_OK, ok)

    def _handle_shm_attach(self, conn, actor_id: int, req: dict) -> None:
        """A colocated actor's ring drains into the upstream batch queue —
        same `flock/shm.py` receiver the service uses, same payload
        contract, one more hop."""
        from .shm import ShmReceiver, ShmRing

        try:
            ring = ShmRing.attach(str(req["name"]))
        except (OSError, KeyError, ValueError) as err:
            wire.send_json(
                conn,
                wire.SHM_ATTACH,
                {"ok": False, "error": f"{type(err).__name__}: {err}"},
            )
            return

        def on_corrupt(_payload, aid=actor_id):
            self._event(
                "flock.shm_corrupt", relay_id=self.relay_id, actor_id=aid
            )

        rx = ShmReceiver(
            ring,
            on_payload=lambda p, aid=actor_id: self._enqueue(aid, p),
            on_corrupt=on_corrupt,
            name=f"flock-relay-shm-{actor_id}",
        )
        with self._lock:
            old = self._shm_rx.get(actor_id)
            self._shm_rx[actor_id] = rx
        if old is not None:
            old.stop(unlink=True)
        rx.start()
        self._event(
            "flock.shm_attached",
            relay_id=self.relay_id,
            actor_id=actor_id,
            ring=ring.name,
        )
        wire.send_json(conn, wire.SHM_ATTACH, {"ok": True})

    # -- observability --------------------------------------------------------

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return {
                "Flock/relay/queue_depth": float(len(self._queue)),
                "Flock/relay/batches": float(self._batches),
                "Flock/relay/forwarded": float(self._forwarded),
                "Flock/relay/dropped": float(self._dropped),
                "Flock/relay/members": float(len(self._members)),
                "Flock/relay/weight_version": float(self._weight_version),
            }

    def _event(self, name: str, **data) -> None:
        if self._telem is not None:
            self._telem.event(name, **data)
        else:
            telemetry.emit(name, **data)


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def main() -> int:
    upstream = os.environ["SHEEPRL_TPU_FLOCK_UPSTREAM"]
    relay_id = int(os.environ.get("SHEEPRL_TPU_FLOCK_RELAY_ID", "0"))
    bind = os.environ.get("SHEEPRL_TPU_FLOCK_RELAY_BIND") or None
    log_dir = os.environ.get("SHEEPRL_TPU_FLOCK_LOG_DIR") or None
    from ..telemetry.core import Telemetry

    telem = (
        Telemetry(log_dir, role=f"relay{relay_id}") if log_dir else None
    )
    relay = Relay(
        upstream=upstream, relay_id=relay_id, bind=bind, telem=telem
    )
    try:
        relay.start()
    except ConnectionError:
        return 0  # no learner to relay for: clean exit, no respawn
    if telem is not None:
        telem.add_gauges(relay.gauges)
    try:
        # serve until the learner goes away for good (fatal) or SIGTERM
        while not relay.fatal.wait(timeout=0.5):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        relay.close()
        if telem is not None:
            telem.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
