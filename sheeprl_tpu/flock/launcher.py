"""Actor subprocess lifecycle: spawn, monitor, respawn.

`ActorFleet` owns N `python -m sheeprl_tpu.flock.actor` children,
configured entirely through environment variables (no argv surface to
drift from the learner's parsed config — the learner's `args.as_dict()`
JSON rides across verbatim). A monitor thread polls the children; a
child that dies with a non-zero/negative return code is respawned (up to
a bounded budget) with a fault-scrubbed environment, reconnects to the
service under its same actor id, and resumes filling its shard — the
learner never restarts, never even blocks.

`retarget_sigkill` implements the sheepfault contract for the flock
topology: a `sigkill@N` clause in `--faults` is retargeted from the
learner onto actor 0 (killing the learner tests nothing about elastic
membership), while every other clause stays learner-side. Respawned
actors ALWAYS get the scrubbed plan so an exactly-once kill cannot
re-fire on the replacement process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

from ..resilience import inject
from ..telemetry import core as telemetry

__all__ = ["ActorFleet", "retarget_sigkill"]

_REPO = Path(__file__).resolve().parents[2]
_POLL_S = 0.5


def retarget_sigkill(args) -> tuple[str, str]:
    """Split the armed fault plan for the flock topology.

    Returns `(learner_text, actor_text)`: the learner re-arms with every
    clause EXCEPT sigkill ones; the sigkill clauses are handed to actor
    0's environment (first spawn only). No plan -> two empty strings."""
    text = os.environ.get(inject.ENV_VAR, "") or ""
    clauses = [c.strip() for c in text.split(",") if c.strip()]
    actor_clauses = [
        c for c in clauses if c.split("@", 1)[0].strip() == "sigkill"
    ]
    learner_clauses = [c for c in clauses if c not in actor_clauses]
    learner_text = ",".join(learner_clauses)
    if actor_clauses:
        # rewrite the exported env BEFORE re-arming (arm_faults re-parses
        # from the environment) so learner-side env workers inherit the
        # scrubbed plan too
        if learner_text:
            os.environ[inject.ENV_VAR] = learner_text
        else:
            os.environ.pop(inject.ENV_VAR, None)
        inject.reset_plan()
        inject.get_plan()
    return learner_text, ",".join(actor_clauses)


class ActorFleet:
    """Spawns and supervises the actor processes of one flock run."""

    def __init__(
        self,
        *,
        algo: str,
        args,
        address: str,
        log_dir: str,
        telem=None,
        actor_faults: str = "",
        max_respawns: int = 3,
    ):
        self.algo = algo
        self.n_actors = int(args.flock)
        self.address = address
        self.log_dir = log_dir
        self._args_json = json.dumps(args.as_dict())
        self._telem = telem
        self._actor_faults = actor_faults
        self._max_respawns = max_respawns
        self._procs: dict[int, subprocess.Popen] = {}
        self._respawns: dict[int, int] = {i: 0 for i in range(self.n_actors)}
        self._logs: dict[int, object] = {}
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        os.makedirs(os.path.join(log_dir, "flock"), exist_ok=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        for actor_id in range(self.n_actors):
            self._spawn(actor_id, first=True)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="flock-monitor", daemon=True
        )
        self._monitor.start()

    def close(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        for proc in self._procs.values():
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 5.0
        for proc in self._procs.values():
            left = max(deadline - time.monotonic(), 0.1)
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        for fh in self._logs.values():
            try:
                fh.close()
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- internals ------------------------------------------------------------

    def _spawn(self, actor_id: int, *, first: bool) -> None:
        env = dict(os.environ)
        env.update(
            SHEEPRL_TPU_FLOCK_ADDR=self.address,
            SHEEPRL_TPU_FLOCK_ACTOR_ID=str(actor_id),
            SHEEPRL_TPU_FLOCK_ALGO=self.algo,
            SHEEPRL_TPU_FLOCK_ARGS=self._args_json,
            SHEEPRL_TPU_FLOCK_LOG_DIR=self.log_dir,
            JAX_PLATFORMS="cpu",
            # actors are telemetry-quiet: the learner's JSONL is the single
            # event stream of the run
            SHEEPRL_TPU_TELEMETRY="0",
        )
        # one actor process needs no forced multi-device cpu topology
        env.pop("XLA_FLAGS", None)
        # the sigkill clause rides ONLY on actor 0's FIRST incarnation: a
        # respawn re-firing the same exactly-once kill would loop forever
        if first and actor_id == 0 and self._actor_faults:
            env[inject.ENV_VAR] = self._actor_faults
        else:
            env.pop(inject.ENV_VAR, None)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (str(_REPO), os.environ.get("PYTHONPATH")) if p
        )
        log_path = os.path.join(
            self.log_dir, "flock", f"actor{actor_id}.log"
        )
        fh = open(log_path, "ab")
        old = self._logs.get(actor_id)
        self._logs[actor_id] = fh
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._procs[actor_id] = subprocess.Popen(
            [sys.executable, "-m", "sheeprl_tpu.flock.actor"],
            env=env,
            stdout=fh,
            stderr=subprocess.STDOUT,
            cwd=str(_REPO),
        )

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            for actor_id, proc in list(self._procs.items()):
                rc = proc.poll()
                if rc is None:
                    continue
                self._event("flock.actor_died", actor_id=actor_id, rc=rc)
                if rc == 0:
                    # clean exit (service closed under it): nothing to heal
                    del self._procs[actor_id]
                    continue
                if self._respawns[actor_id] >= self._max_respawns:
                    self._event(
                        "flock.actor_abandoned",
                        actor_id=actor_id,
                        respawns=self._respawns[actor_id],
                    )
                    del self._procs[actor_id]
                    continue
                self._respawns[actor_id] += 1
                self._spawn(actor_id, first=False)
                self._event(
                    "flock.actor_respawned",
                    actor_id=actor_id,
                    attempt=self._respawns[actor_id],
                )
            self._stop.wait(_POLL_S)

    def alive(self) -> int:
        return sum(1 for p in self._procs.values() if p.poll() is None)

    def _event(self, name: str, **data) -> None:
        if self._telem is not None:
            self._telem.event(name, **data)
        else:
            telemetry.emit(name, **data)
