"""Actor subprocess lifecycle: spawn, monitor, respawn.

`ActorFleet` owns N `python -m sheeprl_tpu.flock.actor` children,
configured entirely through environment variables (no argv surface to
drift from the learner's parsed config — the learner's `args.as_dict()`
JSON rides across verbatim). A monitor thread polls the children; a
child that dies with a non-zero/negative return code is respawned (up to
a bounded budget) with a fault-scrubbed environment, reconnects to the
service under its same actor id, and resumes filling its shard — the
learner never restarts, never even blocks.

With `--relays R` (ISSUE 19) the fleet also owns R
`python -m sheeprl_tpu.flock.relay` children, spawned BEFORE the actors:
actor i gets relay i % R's bind address as its service address, so the
learner holds O(R) data connections however many actors run. Relays are
supervised by the same monitor loop under the same respawn budget; a
respawned relay rebinds its predecessor's unix path, so its actors'
`ResilientLink` reconnect backoff rides straight through the kill —
elastic membership (kill/rejoin, generation bumps) is preserved across
the extra hop because relays FORWARD control frames rather than
answering them.

`retarget_sigkill` implements the sheepfault contract for the flock
topology: `sigkill@N` and `net.*` clauses in `--faults` are retargeted
from the learner onto actor 0 (killing the learner tests nothing about
elastic membership, and under flock the interesting frame sends are the
actor's), while every other clause — including `peer.crash`, which
exists precisely to kill the service host — stays learner-side.
Respawned actors ALWAYS get the scrubbed plan so an exactly-once kill
cannot re-fire on the replacement process.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

from ..resilience import inject
from ..telemetry import core as telemetry

__all__ = ["ActorFleet", "retarget_sigkill"]

_REPO = Path(__file__).resolve().parents[2]
_POLL_S = 0.5


def retarget_sigkill(args) -> tuple[str, str]:
    """Split the armed fault plan for the flock topology.

    Returns `(learner_text, actor_text)`: the learner re-arms with every
    clause EXCEPT sigkill/net.* ones; those are handed to actor 0's
    environment (first spawn only). `peer.crash` deliberately stays
    learner-side — it exists to kill the service HOST. No plan -> two
    empty strings."""
    text = os.environ.get(inject.ENV_VAR, "") or ""
    clauses = [c.strip() for c in text.split(",") if c.strip()]
    actor_clauses = [
        c
        for c in clauses
        if c.split("@", 1)[0].strip() == "sigkill"
        or c.split("@", 1)[0].strip().startswith("net.")
    ]
    learner_clauses = [c for c in clauses if c not in actor_clauses]
    learner_text = ",".join(learner_clauses)
    if actor_clauses:
        # rewrite the exported env BEFORE re-arming (arm_faults re-parses
        # from the environment) so learner-side env workers inherit the
        # scrubbed plan too
        if learner_text:
            os.environ[inject.ENV_VAR] = learner_text
        else:
            os.environ.pop(inject.ENV_VAR, None)
        inject.reset_plan()
        inject.get_plan()
    return learner_text, ",".join(actor_clauses)


class ActorFleet:
    """Spawns and supervises the actor processes of one flock run."""

    def __init__(
        self,
        *,
        algo: str,
        args,
        address: str,
        log_dir: str,
        telem=None,
        actor_faults: str = "",
        max_respawns: int = 3,
    ):
        self.algo = algo
        self.n_actors = int(args.flock)
        self.n_relays = min(
            int(getattr(args, "relays", 0) or 0), self.n_actors
        )
        self.address = address
        self.log_dir = log_dir
        self._args_json = json.dumps(args.as_dict())
        self._telem = telem
        self._actor_faults = actor_faults
        self._max_respawns = max_respawns
        self._procs: dict[int, subprocess.Popen] = {}
        self._adopted: dict[int, int] = {}  # actor_id -> orphan pid
        self._respawns: dict[int, int] = {i: 0 for i in range(self.n_actors)}
        self._logs: dict[int, object] = {}
        self._relay_procs: dict[int, subprocess.Popen] = {}
        self._relay_respawns: dict[int, int] = {
            i: 0 for i in range(self.n_relays)
        }
        self._relay_logs: dict[int, object] = {}
        # relay bind paths live in a short tempdir, not under log_dir: an
        # AF_UNIX path caps at ~107 bytes and run dirs routinely blow that
        self._relay_dir: str | None = None
        self._relay_addrs: dict[int, str] = {}
        if self.n_relays:
            import tempfile

            self._relay_dir = tempfile.mkdtemp(prefix="flock-r-")
            self._relay_addrs = {
                i: f"unix:{self._relay_dir}/r{i}.sock"
                for i in range(self.n_relays)
            }
        # guards _procs/_adopted/_respawns/_logs: handle_eviction arrives on
        # the ReplayService monitor thread while _monitor_loop mutates the
        # same tables (sheepsync SY003). Never held across Popen/kill/wait.
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: threading.Thread | None = None
        os.makedirs(os.path.join(log_dir, "flock"), exist_ok=True)

    # -- lifecycle ------------------------------------------------------------

    def start(self, skip: set[int] = frozenset()) -> None:
        """Spawn every actor not in `skip`. On crash-resume the learner
        skips ids whose pre-crash processes survived the restart and are
        already reconnected — those are `adopt`ed instead of respawned."""
        for relay_id in range(self.n_relays):
            self._spawn_relay(relay_id)
        for actor_id in range(self.n_actors):
            if actor_id not in skip:
                self._spawn(actor_id, first=True)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="flock-monitor", daemon=True
        )
        self._monitor.start()

    def adopt(self, actor_id: int, pid: int) -> None:
        """Track a surviving pre-crash actor process this fleet did not
        spawn, so `close()` still tears it down with the rest."""
        if pid > 0:
            with self._lock:
                self._adopted[actor_id] = pid
            self._event("flock.actor_adopted", actor_id=actor_id, pid=pid)

    def handle_eviction(self, actor_id: int) -> None:
        """`ReplayService.on_evict` hook: a heartbeat-stale actor is
        treated like a death — kill the wedged process (the monitor loop
        then applies the normal respawn budget)."""
        with self._lock:
            proc = self._procs.get(actor_id)
        if proc is not None and proc.poll() is None:
            proc.kill()
            return
        # an adopted orphan has no Popen handle for the monitor loop:
        # respawn it here under the same budget. Budget bookkeeping under
        # the lock; the kill and respawn on the local copies outside it.
        with self._lock:
            pid = self._adopted.pop(actor_id, None)
            respawn = pid is not None and (
                self._respawns[actor_id] < self._max_respawns
            )
            if respawn:
                self._respawns[actor_id] += 1
            attempt = self._respawns[actor_id]
        if pid is None:
            return
        self._kill_pid(pid)
        if respawn:
            self._spawn(actor_id, first=False)
            self._event(
                "flock.actor_respawned", actor_id=actor_id, attempt=attempt
            )
        else:
            self._event(
                "flock.actor_abandoned", actor_id=actor_id, respawns=attempt
            )

    @staticmethod
    def _kill_pid(pid: int) -> None:
        import signal as _signal

        for sig in (_signal.SIGTERM, _signal.SIGKILL):
            try:
                os.kill(pid, sig)
            except ProcessLookupError:
                return
            except OSError:
                return
            time.sleep(0.2)

    def close(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        # snapshot under the lock, tear down on the snapshot: the monitor
        # thread is joined above, but handle_eviction can still arrive from
        # the service's monitor thread until the service itself closes
        with self._lock:
            procs = list(self._procs.values()) + list(
                self._relay_procs.values()
            )
            adopted = list(self._adopted.values())
            logs = list(self._logs.values()) + list(self._relay_logs.values())
        for proc in procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 5.0
        for proc in procs:
            left = max(deadline - time.monotonic(), 0.1)
            try:
                proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
        for pid in adopted:
            self._kill_pid(pid)
        for fh in logs:
            try:
                fh.close()
            except OSError:
                pass
        if self._relay_dir:
            import shutil

            shutil.rmtree(self._relay_dir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- internals ------------------------------------------------------------

    def _actor_address(self, actor_id: int) -> str:
        """The address actor `actor_id` dials: its relay's bind when the
        topology has relays, the service itself otherwise."""
        if self.n_relays:
            return self._relay_addrs[actor_id % self.n_relays]
        return self.address

    def _spawn_relay(self, relay_id: int) -> None:
        from ..telemetry.trace import RUN_ENV, ensure_run_id

        env = dict(os.environ)
        env.update(
            SHEEPRL_TPU_FLOCK_UPSTREAM=self.address,
            SHEEPRL_TPU_FLOCK_RELAY_ID=str(relay_id),
            SHEEPRL_TPU_FLOCK_RELAY_BIND=self._relay_addrs[relay_id],
            SHEEPRL_TPU_FLOCK_LOG_DIR=self.log_dir,
            JAX_PLATFORMS="cpu",
        )
        env[RUN_ENV] = ensure_run_id()
        env.pop("XLA_FLAGS", None)
        # fault clauses ride on the learner or actor 0, never a relay: the
        # relay chaos coverage injects in-process (tests) or kills the
        # relay outright (CI smoke)
        env.pop(inject.ENV_VAR, None)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (str(_REPO), os.environ.get("PYTHONPATH")) if p
        )
        log_path = os.path.join(self.log_dir, "flock", f"relay{relay_id}.log")
        fh = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "sheeprl_tpu.flock.relay"],
            env=env,
            stdout=fh,
            stderr=subprocess.STDOUT,
            cwd=str(_REPO),
        )
        with self._lock:
            old = self._relay_logs.get(relay_id)
            self._relay_logs[relay_id] = fh
            self._relay_procs[relay_id] = proc
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def _spawn(self, actor_id: int, *, first: bool) -> None:
        from ..telemetry.trace import RUN_ENV, ensure_run_id

        env = dict(os.environ)
        env.update(
            SHEEPRL_TPU_FLOCK_ADDR=self._actor_address(actor_id),
            SHEEPRL_TPU_FLOCK_ACTOR_ID=str(actor_id),
            SHEEPRL_TPU_FLOCK_ALGO=self.algo,
            SHEEPRL_TPU_FLOCK_ARGS=self._args_json,
            SHEEPRL_TPU_FLOCK_LOG_DIR=self.log_dir,
            JAX_PLATFORMS="cpu",
        )
        # sheepscope (ISSUE 17): each actor writes its own
        # telemetry.actor{N}.jsonl shard into the shared run dir, keyed by
        # the learner's run id so sheeptrace merges them onto one timeline
        env[RUN_ENV] = ensure_run_id()
        # one actor process needs no forced multi-device cpu topology
        env.pop("XLA_FLAGS", None)
        # the sigkill clause rides ONLY on actor 0's FIRST incarnation: a
        # respawn re-firing the same exactly-once kill would loop forever
        if first and actor_id == 0 and self._actor_faults:
            env[inject.ENV_VAR] = self._actor_faults
        else:
            env.pop(inject.ENV_VAR, None)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (str(_REPO), os.environ.get("PYTHONPATH")) if p
        )
        log_path = os.path.join(
            self.log_dir, "flock", f"actor{actor_id}.log"
        )
        fh = open(log_path, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "sheeprl_tpu.flock.actor"],
            env=env,
            stdout=fh,
            stderr=subprocess.STDOUT,
            cwd=str(_REPO),
        )
        with self._lock:
            old = self._logs.get(actor_id)
            self._logs[actor_id] = fh
            self._procs[actor_id] = proc
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                snapshot = list(self._procs.items())
            for actor_id, proc in snapshot:
                rc = proc.poll()
                if rc is None:
                    continue
                self._event("flock.actor_died", actor_id=actor_id, rc=rc)
                with self._lock:
                    if rc == 0 or self._respawns[actor_id] >= self._max_respawns:
                        # clean exit (service closed under it) or budget
                        # exhausted: nothing to heal
                        self._procs.pop(actor_id, None)
                        respawn = False
                    else:
                        self._respawns[actor_id] += 1
                        respawn = True
                    attempt = self._respawns[actor_id]
                if respawn:
                    self._spawn(actor_id, first=False)
                    self._event(
                        "flock.actor_respawned",
                        actor_id=actor_id,
                        attempt=attempt,
                    )
                elif rc != 0:
                    self._event(
                        "flock.actor_abandoned",
                        actor_id=actor_id,
                        respawns=attempt,
                    )
            with self._lock:
                relay_snapshot = list(self._relay_procs.items())
            for relay_id, proc in relay_snapshot:
                rc = proc.poll()
                if rc is None:
                    continue
                self._event("flock.relay_died", relay_id=relay_id, rc=rc)
                with self._lock:
                    if (
                        rc == 0
                        or self._relay_respawns[relay_id] >= self._max_respawns
                    ):
                        self._relay_procs.pop(relay_id, None)
                        respawn = False
                    else:
                        self._relay_respawns[relay_id] += 1
                        respawn = True
                    attempt = self._relay_respawns[relay_id]
                if respawn:
                    # same bind path: the relay's actors reconnect through
                    # their normal backoff, no address redistribution
                    self._spawn_relay(relay_id)
                    self._event(
                        "flock.relay_respawned",
                        relay_id=relay_id,
                        attempt=attempt,
                    )
                elif rc != 0:
                    self._event(
                        "flock.relay_abandoned",
                        relay_id=relay_id,
                        respawns=attempt,
                    )
            self._stop.wait(_POLL_S)

    def relays_alive(self) -> int:
        with self._lock:
            procs = list(self._relay_procs.values())
        return sum(1 for p in procs if p.poll() is None)

    def alive(self) -> int:
        with self._lock:
            procs = list(self._procs.values())
            adopted = list(self._adopted.values())
        n = sum(1 for p in procs if p.poll() is None)
        for pid in adopted:
            try:
                os.kill(pid, 0)
            except OSError:
                continue
            n += 1
        return n

    def _event(self, name: str, **data) -> None:
        if self._telem is not None:
            self._telem.event(name, **data)
        else:
            telemetry.emit(name, **data)
