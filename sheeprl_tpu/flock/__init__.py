"""sheepflock — multi-process Sebulba actor-learner runtime (ISSUE 14).

Podracer's Sebulba arrangement (arXiv:2104.06272) on this repo's pieces:
N actor processes run the task's existing collection loop and stream
rollout chunks over a length-prefixed socket into a **replay service**
hosted inside the learner process — one shard (an ordinary
`data/buffers.py` buffer) per actor, so the learner samples locally with
NO socket on the sample path. Weights flow the other way as versioned
snapshots pulled off the actors' hot path. Membership is elastic: actors
register/heartbeat/deregister, the learner keeps training through an
actor death (the sheepfault `sigkill` site), and a respawned actor
rejoins at the current weight version without a learner restart.

Module map:
    wire.py      socket frame protocol (pickle-free, `data/wire.py` payloads)
    sizing.py    per-actor shard capacities from the sheepmem ledger
    service.py   learner-side replay service + membership + gauges
    actor.py     actor process entry (`python -m sheeprl_tpu.flock.actor`)
    launcher.py  actor/relay subprocess lifecycle: spawn, monitor, respawn
    shm.py       zero-copy shared-memory ring transport for colocated actors
    relay.py     hierarchical aggregation hop (`--relays R`, ISSUE 19)
    assemble.py  in-network sample pre-assembly across shards (ISSUE 19)

Wired behind `--flock {off,N}` in `ppo` and `dreamer_v3`; `--flock off`
is bit-exact vs the in-process path (checkpoint-parity test-gated).
Scale-out (ISSUE 19): `--relays R` inserts an aggregation tier,
`SHEEPRL_TPU_FLOCK_SHM` moves colocated actors' bulk pushes onto
shared-memory rings, and `--pipeline on` pre-assembles sample batches
across shards — see howto/distributed_actors.md.
"""

from .assemble import BatchAssembler
from .launcher import ActorFleet, retarget_sigkill
from .relay import Relay
from .service import ReplayService
from .shm import ShmReceiver, ShmRing, shm_enabled_for
from .sizing import shard_capacity

__all__ = [
    "ActorFleet",
    "BatchAssembler",
    "Relay",
    "ReplayService",
    "ShmReceiver",
    "ShmRing",
    "retarget_sigkill",
    "shard_capacity",
    "shm_enabled_for",
]
