"""Flock actor process entry: `python -m sheeprl_tpu.flock.actor`.

One actor runs the task's EXISTING host-env collection loop — the same
`policy_step` / player-step jits the in-process mains use — against a
local copy of the policy, and streams rollout data to the learner's
replay service over the `flock/wire.py` socket protocol. Configuration
arrives via environment variables (set by `launcher.ActorFleet`):

    SHEEPRL_TPU_FLOCK_ADDR       service address (tcp:HOST:PORT | unix:PATH)
    SHEEPRL_TPU_FLOCK_ACTOR_ID   this actor's integer id
    SHEEPRL_TPU_FLOCK_ALGO       'ppo' | 'dreamer_v3'
    SHEEPRL_TPU_FLOCK_ARGS       JSON of the learner's `args.as_dict()`
    SHEEPRL_TPU_FLOCK_LOG_DIR    run directory (env video/media side files)

Weight pulls ride a SECOND connection serviced by a background thread
(`WeightFetcher`), so a snapshot transfer never sits inside the env-step
loop; the loop swaps a landed version in between steps. The actor builds
its model with the same constructors the learner uses — only the
flattened leaves cross the wire, never a treedef, never a pickle.

Faults: the actor arms `SHEEPRL_TPU_FAULTS` from its (launcher-scrubbed)
environment and fires the `sigkill` site from its step loop — the
elastic-membership receipt the CI fault-smoke scenario kills. The `net.*`
sites fire inside `flock/wire.py` on this process's own frame sends.

Reconnection (ISSUE 16): a dead data socket (learner crash, injected
partition) is NOT fatal — `ResilientLink` reconnects with capped
exponential backoff bounded by `SHEEPRL_TPU_FLOCK_RECONNECT_S` (default
120 s, sized to ride out a learner restart including jax bring-up),
re-HELLOs (the service bumps the generation), and re-pushes the in-flight
chunk, so no collected row is lost to a transient. Only an exhausted
budget exits the process (rc 0: the learner is really gone).

Observability (ISSUE 17, sheepscope): each actor runs a real Telemetry
instance writing its own `telemetry.actor{N}.jsonl` shard into the shared
run directory, keyed by the run id the launcher exports. Collect/push
spans carry trace context on the PUSH meta (ingested by the service into
the learner's shard), HEARTBEATs piggyback monotonic + wall send stamps
(sender-clock eviction ages and NTP-style clock-offset estimation), and
SIGUSR2 opens a bounded on-demand `jax.profiler` window.
`tools/sheeptrace.py` merges the shards into one timeline.
"""

from __future__ import annotations

import json
import os
import signal
import struct
import sys
import tempfile
import threading
import time

# actors are host-collection processes: pin the cpu backend before jax
# initializes (the learner owns whatever accelerator the run targets)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

from ..resilience import inject
from . import wire
from .service import PROTO_VERSION, pack_push
from .shm import shm_enabled_for

_U32 = struct.Struct("<I")

PUSH_EVERY_ROWS = 8  # dv3: rows buffered per PUSH frame
HEARTBEAT_S = 1.0
WEIGHT_POLL_S = 0.25

RECONNECT_VAR = "SHEEPRL_TPU_FLOCK_RECONNECT_S"
DEFAULT_RECONNECT_S = 120.0
BACKOFF_BASE_S = 0.25
BACKOFF_CAP_S = 5.0


class WeightFetcher(threading.Thread):
    """Polls GET_WEIGHTS on a dedicated connection; holds the newest
    landed (version, leaves) for the step loop to swap in. A timed-out or
    failed poll keeps the old weights — the PR-12 `to_player` deadline
    semantics: degrade to staleness, never stall the actor."""

    def __init__(self, addr: str, actor_id: int, timeout: float | None):
        super().__init__(name=f"flock-weights-{actor_id}", daemon=True)
        self._addr = addr
        self._actor_id = actor_id
        self._timeout = timeout
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.version = -1
        # sheepscope: publish span id riding the newest WEIGHTS meta — the
        # collect span for data gathered under that version parents on it
        self.last_span: str | None = None
        self._leaves: list[np.ndarray] | None = None

    def take(self):
        """-> (version, leaves) of the newest unconsumed snapshot, or
        (None, None). Consuming clears the slot."""
        with self._lock:
            leaves, self._leaves = self._leaves, None
            return (self.version, leaves) if leaves is not None else (None, None)

    def stop(self, join_timeout: float = 5.0) -> None:
        """Signal the poll loop and join it (bounded — the socket ops all
        carry timeouts, so the loop observes the event within one poll
        interval; sheepsync satellite: no unjoined thread survives the
        actor's shutdown path)."""
        self._stop.set()
        if self.is_alive():
            self.join(timeout=join_timeout)

    def run(self) -> None:
        sock = None
        while not self._stop.is_set():
            try:
                if sock is None:
                    sock = wire.connect(self._addr, timeout=self._timeout)
                    wire.send_json(
                        sock,
                        wire.HELLO,
                        {
                            "actor_id": self._actor_id,
                            "pid": os.getpid(),
                            "role": "weights",
                            "proto": PROTO_VERSION,
                        },
                    )
                wire.send_json(
                    sock, wire.GET_WEIGHTS, {"have_version": self.version}
                )
                frame = wire.recv_frame(sock)
                if frame is None:
                    # service gone — maybe restarting at the same address:
                    # drop the socket and keep polling (the data link's
                    # reconnect budget bounds how long the actor waits)
                    raise ConnectionResetError("weights connection closed")
                kind, payload = frame
                if kind == wire.WEIGHTS:
                    (meta_len,) = _U32.unpack_from(payload, 0)
                    meta = json.loads(payload[4 : 4 + meta_len].decode())
                    from ..data.wire import unpack_leaves

                    leaves = unpack_leaves(payload[4 + meta_len :])
                    with self._lock:
                        self.version = int(meta["version"])
                        self.last_span = meta.get("span")
                        self._leaves = leaves
            except (OSError, wire.FrameError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
            self._stop.wait(WEIGHT_POLL_S)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class _ServiceLink:
    """The actor's data connection: HELLO/WELCOME handshake, then strictly
    sequential PUSH and HEARTBEAT request/replies from the step loop.
    HEARTBEATs carry monotonic + wall send stamps; the service's
    `server_wall_ts` reply feeds the optional `ClockSync` (sheepscope).

    With `use_shm` (ISSUE 19) the first push lazily creates a
    shared-memory ring sized off its payload (flock/shm.py), attaches it
    over the socket (SHM_ATTACH), and routes every subsequent PUSH
    payload through the ring — the socket keeps carrying heartbeats and
    BYE. Any ring failure (attach refused, ring full past its bounded
    wait, oversize frame, injected partition) falls back to the socket
    path for that push; a partition disables shm for the link's lifetime
    so the reconnect genuinely exercises the socket."""

    def __init__(
        self,
        addr: str,
        actor_id: int,
        timeout: float | None,
        clock=None,
        use_shm: bool = False,
    ):
        self.sock = wire.connect(addr, timeout=timeout)
        self._clock = clock
        self._use_shm = use_shm
        self._ring = None
        wire.send_json(
            self.sock,
            wire.HELLO,
            {
                "actor_id": actor_id,
                "pid": os.getpid(),
                "role": "data",
                "proto": PROTO_VERSION,
            },
        )
        self.welcome = wire.recv_json(self.sock, wire.WELCOME)
        self.random_phase = bool(self.welcome.get("random_phase"))
        self._last_hb = time.monotonic()
        self._hb_steps0 = 0
        self._hb_t0 = time.monotonic()

    def _attach_ring(self, first_payload_len: int) -> None:
        """Create + announce the ring; one shot — a refusal (old service,
        attach error) permanently reverts this link to the socket."""
        from .shm import ShmRing, ring_geometry

        self._use_shm = False  # re-enabled only on an ok reply
        slots, slot_bytes = ring_geometry(first_payload_len)
        ring = ShmRing.create(slots=slots, slot_bytes=slot_bytes)
        try:
            wire.send_json(
                self.sock,
                wire.SHM_ATTACH,
                {
                    "actor_id": self.welcome["actor_id"],
                    "name": ring.name,
                    "slots": slots,
                    "slot_bytes": slot_bytes,
                },
            )
            reply = wire.recv_json(self.sock, wire.SHM_ATTACH)
        except (OSError, wire.FrameError):
            ring.close(unlink=True)
            raise
        if reply.get("ok"):
            self._ring = ring
            self._use_shm = True
        else:
            ring.close(unlink=True)

    def _detach_ring(self) -> None:
        if self._ring is not None:
            # unlink only the NAME: the service's drain thread still holds
            # a mapping and unlinks defensively on its own teardown
            self._ring.close(unlink=True)
            self._ring = None
        self._use_shm = False

    def _push_shm(self, payload: bytes) -> bool:
        """Commit one PUSH payload to the ring; False -> use the socket
        for this frame. Raises ConnectionResetError on injected partition
        (after disabling shm for this link)."""
        import zlib

        crc = zlib.crc32(payload)
        try:
            data = wire.inject_shm_send(payload)
        except ConnectionResetError:
            self._detach_ring()
            raise
        if data is None:
            return True  # injected net.drop: the frame is lost, by design
        return self._ring.push(data, crc=crc)

    def push(
        self,
        ops,
        *,
        rows: int,
        env_steps: int,
        weight_version: int,
        trace: dict | None = None,
    ):
        payload = pack_push(
            ops,
            rows=rows,
            env_steps=env_steps,
            weight_version=weight_version,
            trace=trace,
        )
        if self._use_shm and self._ring is None:
            self._attach_ring(len(payload))
        if self._use_shm and self._ring is not None:
            if self._push_shm(payload):
                # no per-push reply on the ring path: random_phase and
                # weight_version updates ride the 1 Hz heartbeats
                return {"shm": True, "random_phase": self.random_phase}
        wire.send_frame(self.sock, wire.PUSH, payload)
        reply = wire.recv_json(self.sock, wire.PUSH_OK)
        self.random_phase = bool(reply.get("random_phase"))
        return reply

    def maybe_heartbeat(self, env_steps: int, weight_version: int) -> None:
        now = time.monotonic()
        if now - self._last_hb < HEARTBEAT_S:
            return
        dt = max(now - self._hb_t0, 1e-9)
        sps = (env_steps - self._hb_steps0) / dt
        self._hb_t0, self._hb_steps0 = now, env_steps
        self._last_hb = now
        t0 = time.time()
        wire.send_json(
            self.sock,
            wire.HEARTBEAT,
            {
                "actor_id": self.welcome["actor_id"],
                "env_steps": env_steps,
                "weight_version": weight_version,
                "sps": sps,
                # sender-clock stamps: mono feeds cross-host-safe eviction
                # ages on the service, wall feeds the clock-offset estimate
                "mono_ts": time.monotonic(),
                "wall_ts": t0,
            },
        )
        reply = wire.recv_json(self.sock, wire.HEARTBEAT_OK)
        self.random_phase = bool(reply.get("random_phase"))
        server_wall = reply.get("server_wall_ts")
        if server_wall is not None and self._clock is not None:
            self._clock.add(t0, float(server_wall), time.time())

    def close(self) -> None:
        try:
            wire.send_json(
                self.sock, wire.BYE, {"actor_id": self.welcome["actor_id"]}
            )
        except OSError:
            pass
        self._detach_ring()
        try:
            self.sock.close()
        except OSError:
            pass


def _reconnect_budget() -> float:
    return float(os.environ.get(RECONNECT_VAR, DEFAULT_RECONNECT_S))


def _connect_with_backoff(
    addr: str,
    actor_id: int,
    timeout: float | None,
    clock=None,
    use_shm: bool = False,
) -> _ServiceLink:
    """Dial the service until it answers: capped exponential backoff
    (0.25 s doubling to 5 s) bounded by the total reconnect budget. An
    injected `net.partition` window refuses `wire.connect` outright, so
    the backoff genuinely waits the partition out."""
    budget = _reconnect_budget()
    deadline = time.monotonic() + budget
    delay = BACKOFF_BASE_S
    last: Exception | None = None
    while True:
        try:
            return _ServiceLink(
                addr, actor_id, timeout, clock=clock, use_shm=use_shm
            )
        except (OSError, TimeoutError) as err:
            last = err
            left = deadline - time.monotonic()
            if left <= 0:
                raise ConnectionError(
                    f"flock service {addr!r} unreachable after "
                    f"{budget:.0f}s (last: {type(last).__name__}: {last})"
                ) from err
            time.sleep(min(delay, left))
            delay = min(delay * 2.0, BACKOFF_CAP_S)


class ResilientLink:
    """`_ServiceLink` that survives the service going away: every failed
    push/heartbeat closes the socket, reconnects with backoff (re-HELLO ->
    the service bumps this actor's generation), and re-pushes the chunk
    that was in flight — PUSH frames are self-contained, so a replayed
    chunk after a learner restore is new data, never a duplicate commit."""

    _RETRIES = 3  # fresh backoff-bounded connection per attempt

    def __init__(
        self,
        addr: str,
        actor_id: int,
        timeout: float | None,
        clock=None,
        use_shm: bool = False,
    ):
        self._addr = addr
        self._actor_id = actor_id
        self._timeout = timeout
        self._clock = clock
        self._use_shm = use_shm
        self._link = _connect_with_backoff(
            addr, actor_id, timeout, clock=clock, use_shm=use_shm
        )

    @property
    def welcome(self) -> dict:
        return self._link.welcome

    @property
    def random_phase(self) -> bool:
        return self._link.random_phase

    def _reconnect(self) -> None:
        # a link that disabled shm on itself (injected partition, refused
        # attach) keeps it disabled across reconnects: the fallback must
        # stay on the socket path it degraded to
        self._use_shm = self._use_shm and self._link._use_shm
        self._link._detach_ring()
        try:
            self._link.sock.close()
        except OSError:
            pass
        self._link = _connect_with_backoff(
            self._addr, self._actor_id, self._timeout, clock=self._clock,
            use_shm=self._use_shm,
        )

    def push(
        self,
        ops,
        *,
        rows: int,
        env_steps: int,
        weight_version: int,
        trace: dict | None = None,
    ):
        for attempt in range(self._RETRIES):
            try:
                return self._link.push(
                    ops,
                    rows=rows,
                    env_steps=env_steps,
                    weight_version=weight_version,
                    trace=trace,
                )
            except (OSError, TimeoutError):
                if attempt == self._RETRIES - 1:
                    raise
                self._reconnect()

    def maybe_heartbeat(self, env_steps: int, weight_version: int) -> None:
        try:
            self._link.maybe_heartbeat(env_steps, weight_version)
        except (OSError, TimeoutError):
            # heartbeats are disposable — reconnect, don't replay
            self._reconnect()

    def close(self) -> None:
        self._link.close()


def _observe(telem):
    """-> (tracer, clock) for a runner. A missing Telemetry (direct
    library calls, old tests) degrades to a disabled shard: every span
    call no-ops, nothing is written."""
    from ..telemetry.core import Telemetry
    from ..telemetry.trace import ClockSync

    if telem is None:
        telem = Telemetry(None, enabled=False)
    return telem.tracer, ClockSync(telem)


def _push_trace(push_span, actor_id: int) -> dict | None:
    """PUSH frame trace context. `mono_ts` rides along so in-flight pushes
    advance the service's sender-clock liveness just like heartbeats."""
    if push_span is None:
        return None
    return {
        "span": push_span.id,
        "actor": actor_id,
        "mono_ts": time.monotonic(),
    }


def _transfer_timeout() -> float | None:
    raw = os.environ.get("SHEEPRL_TPU_TRANSFER_TIMEOUT_S")
    if not raw:
        return 30.0
    val = float(raw)
    return val if val > 0 else None


def _fire_faults(step: int) -> None:
    """The flock `sigkill` site: an armed plan kills THIS actor process
    dead (no cleanup, no goodbye) — exactly the failure mode the elastic
    membership path must absorb."""
    spec = inject.get_plan().fire_at("sigkill", step)
    if spec is not None:
        os.kill(os.getpid(), signal.SIGKILL)


def _wait_initial_weights(fetcher: WeightFetcher, timeout: float = 120.0):
    """Block until the learner's first published snapshot lands: actors
    must never collect on their private random init (PPO is on-policy)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        version, leaves = fetcher.take()
        if leaves is not None:
            return version, leaves
        time.sleep(0.05)
    raise TimeoutError("no initial weight snapshot from the flock service")


def _make_envs(args, actor_id: int, log_dir: str, *, mask_vel: bool = False):
    from ..envs import make_vector_env
    from ..utils.env import make_dict_env

    # decorrelated env seeds per actor; same offset scheme every rejoin, so
    # a respawned actor replays its own env stream rather than a fresh draw
    seed0 = args.seed + 1009 * (actor_id + 1)
    kw = {"mask_velocities": args.mask_vel} if mask_vel else {}
    return make_vector_env(
        [
            make_dict_env(
                args.env_id, seed0 + i, rank=actor_id, args=args,
                run_name=log_dir, vector_env_idx=i, **kw,
            )
            for i in range(args.num_envs)
        ],
        sync=args.sync_env or args.num_envs == 1,
    ), seed0


# ---------------------------------------------------------------------------
# ppo
# ---------------------------------------------------------------------------


def run_ppo(args, actor_id: int, addr: str, log_dir: str, telem=None) -> None:
    from ..algos.ppo.agent import (
        PPOAgent,
        buffer_actions,
        indices_to_env_actions,
    )
    from ..algos.ppo.ppo import actions_dim_of, policy_step, validate_obs_keys

    envs, seed0 = _make_envs(args, actor_id, log_dir, mask_vel=True)
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    cnn_keys, mlp_keys = validate_obs_keys(observation_space, args)
    obs_keys = [*cnn_keys, *mlp_keys]
    actions_dim, is_continuous = actions_dim_of(action_space)

    # same constructor call as the learner -> same pytree structure; the
    # random init below never acts (first snapshot is awaited), it only
    # donates the treedef the wire leaves unflatten into
    key = jax.random.PRNGKey(seed0)
    key, agent_key = jax.random.split(key)
    agent = PPOAgent.init(
        agent_key, actions_dim, observation_space.spaces,
        cnn_keys, mlp_keys,
        cnn_features_dim=args.cnn_features_dim, mlp_features_dim=args.mlp_features_dim,
        screen_size=args.screen_size, mlp_layers=args.mlp_layers,
        dense_units=args.dense_units, dense_act=args.dense_act,
        layer_norm=args.layer_norm, is_continuous=is_continuous,
        actor_hidden_size=args.actor_hidden_size,
        critic_hidden_size=args.critic_hidden_size,
        cnn_channels_multiplier=args.cnn_channels_multiplier,
        precision=args.precision,
    )
    treedef = jax.tree_util.tree_structure(agent)

    tracer, clock = _observe(telem)
    timeout = _transfer_timeout()
    fetcher = WeightFetcher(addr, actor_id, timeout)
    fetcher.start()
    link = ResilientLink(
        addr, actor_id, timeout, clock=clock,
        use_shm=shm_enabled_for(actor_id),
    )
    version, leaves = _wait_initial_weights(fetcher)
    agent = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(x) for x in leaves])

    T = args.rollout_steps
    obs, _ = envs.reset(seed=seed0)
    next_done = np.zeros(args.num_envs, dtype=np.float32)
    env_steps = 0
    step_counter = 0
    try:
        while True:
            # collect span: one whole rollout chunk, parented on the publish
            # span of the weights it acts with (the provenance chain's root)
            collect = tracer.begin(
                "collect", parent=fetcher.last_span, actor=actor_id
            )
            chunk: dict[str, list] = {k: [] for k in obs_keys}
            for extra in ("actions", "logprobs", "values", "rewards", "dones"):
                chunk[extra] = []
            for _ in range(T):
                step_counter += 1
                _fire_faults(step_counter)
                # swap in a landed snapshot between steps: a chunk may mix
                # adjacent versions — fine for PPO, whose recorded
                # logprobs/values stay consistent with the acting policy
                new_version, new_leaves = fetcher.take()
                if new_leaves is not None:
                    version = new_version
                    agent = jax.tree_util.tree_unflatten(
                        treedef, [jnp.asarray(x) for x in new_leaves]
                    )
                key, step_key = jax.random.split(key)
                device_obs = {k: jnp.asarray(obs[k]) for k in obs_keys}
                actions, logprob, value, env_idx = policy_step(
                    agent, device_obs, step_key
                )
                env_idx_np = np.asarray(env_idx)
                env_actions = indices_to_env_actions(
                    env_idx_np, actions_dim, is_continuous
                )
                next_obs, rewards, terms, truncs, _infos = envs.step(
                    list(env_actions)
                )
                dones = (terms | truncs).astype(np.float32)
                for k in obs_keys:
                    chunk[k].append(np.asarray(obs[k]))
                chunk["actions"].append(
                    np.asarray(
                        buffer_actions(
                            env_idx_np, actions, actions_dim, is_continuous,
                            host=True,
                        ),
                        np.float32,
                    )
                )
                lv = np.asarray(jnp.concatenate([logprob, value], axis=-1))
                chunk["logprobs"].append(lv[:, :1])
                chunk["values"].append(lv[:, 1:])
                chunk["rewards"].append(
                    np.asarray(rewards, np.float32)[:, None]
                )
                chunk["dones"].append(next_done[:, None].copy())
                next_done = dones
                obs = next_obs
                env_steps += args.num_envs
                link.maybe_heartbeat(env_steps, version)
            # bootstrap row T: the obs/done entering the NEXT step — the
            # learner's GAE tail; other slots are zero-filled padding
            for k in obs_keys:
                chunk[k].append(np.asarray(obs[k]))
            chunk["dones"].append(next_done[:, None].copy())
            for extra in ("actions", "logprobs", "values", "rewards"):
                chunk[extra].append(np.zeros_like(chunk[extra][0]))
            tree = {k: np.stack(v) for k, v in chunk.items()}
            collect_id = tracer.end(
                collect, rows=T, env_steps=env_steps, weight_version=version
            )
            push = tracer.begin("push", parent=collect_id, actor=actor_id)
            link.push(
                [(tree, None)],
                rows=T,
                env_steps=env_steps,
                weight_version=version,
                trace=_push_trace(push, actor_id),
            )
            tracer.end(push, rows=T, weight_version=version)
    finally:
        fetcher.stop()
        link.close()
        envs.close()


# ---------------------------------------------------------------------------
# dreamer_v3
# ---------------------------------------------------------------------------


def run_dreamer_v3(
    args, actor_id: int, addr: str, log_dir: str, telem=None
) -> None:
    from ..algos.dreamer_v3.agent import PlayerDV3, build_models
    from ..algos.dreamer_v3.dreamer_v3 import _random_actions
    from ..algos.dreamer_v3.utils import make_device_preprocess
    from ..algos.ppo.agent import (
        buffer_actions,
        env_action_indices,
        indices_to_env_actions,
    )
    from ..algos.ppo.ppo import actions_dim_of, validate_obs_keys

    envs, seed0 = _make_envs(args, actor_id, log_dir)
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    cnn_keys, mlp_keys = validate_obs_keys(observation_space, args)
    obs_keys = [*cnn_keys, *mlp_keys]
    actions_dim, is_continuous = actions_dim_of(action_space)
    act_sum = int(sum(actions_dim))

    key = jax.random.PRNGKey(seed0)
    key, model_key = jax.random.split(key)
    world_model, dv3_actor, _critic, _target = build_models(
        model_key, actions_dim, is_continuous, args,
        observation_space.spaces, cnn_keys, mlp_keys,
    )
    # the published snapshot is the PLAYER's leaves (encoder+rssm+actor):
    # the critic/optimizer halves of the train state never leave the learner
    player = PlayerDV3(
        encoder=world_model.encoder,
        rssm=world_model.rssm,
        actor=dv3_actor,
        actions_dim=tuple(actions_dim),
        stochastic_size=args.stochastic_size,
        discrete_size=args.discrete_size,
        recurrent_state_size=args.recurrent_state_size,
        is_continuous=is_continuous,
        compute_dtype=args.precision,
    )
    treedef = jax.tree_util.tree_structure(player)
    _dev_preprocess = make_device_preprocess(cnn_keys)

    def _player_step(p, s, o, k, expl, mask):
        new_s, acts = p.step(
            s, _dev_preprocess(o), k, expl, is_training=True, mask=mask
        )
        return new_s, acts, env_action_indices(acts, actions_dim, is_continuous)

    player_step = jax.jit(_player_step)

    tracer, clock = _observe(telem)
    timeout = _transfer_timeout()
    fetcher = WeightFetcher(addr, actor_id, timeout)
    fetcher.start()
    link = ResilientLink(
        addr, actor_id, timeout, clock=clock,
        use_shm=shm_enabled_for(actor_id),
    )
    version, leaves = _wait_initial_weights(fetcher)
    player = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(x) for x in leaves]
    )
    player_state = player.init_states(args.num_envs)
    expl_dev = jnp.float32(args.expl_amount)

    obs, _ = envs.reset(seed=seed0)
    step_data = {k: np.asarray(obs[k]) for k in obs_keys}
    step_data["dones"] = np.zeros((args.num_envs, 1), np.float32)
    step_data["rewards"] = np.zeros((args.num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((args.num_envs, 1), np.float32)

    ops: list[tuple[dict, list | None]] = []
    rows_pending = 0
    env_steps = 0
    step_counter = 0
    try:
        while True:
            step_counter += 1
            _fire_faults(step_counter)
            new_version, new_leaves = fetcher.take()
            if new_leaves is not None:
                version = new_version
                player = jax.tree_util.tree_unflatten(
                    treedef, [jnp.asarray(x) for x in new_leaves]
                )
            if link.random_phase:
                pairs = [
                    _random_actions(action_space, actions_dim, is_continuous)
                    for _ in range(args.num_envs)
                ]
                actions = np.stack([p[0] for p in pairs])
                env_actions = [p[1] for p in pairs]
            else:
                device_obs = {
                    k: jnp.asarray(np.asarray(obs[k])) for k in obs_keys
                }
                mask = {
                    k: v for k, v in device_obs.items() if k.startswith("mask")
                } or None
                key, step_key = jax.random.split(key)
                player_state, actions_dev, env_idx_dev = player_step(
                    player, player_state, device_obs, step_key, expl_dev, mask
                )
                env_idx = np.asarray(env_idx_dev)
                env_actions = list(
                    indices_to_env_actions(env_idx, actions_dim, is_continuous)
                )
                actions = buffer_actions(
                    env_idx, actions_dev, actions_dim, is_continuous, host=True
                )
            step_data["actions"] = np.asarray(actions, np.float32)
            ops.append(({k: v[None].copy() for k, v in step_data.items()}, None))
            rows_pending += 1

            next_obs, rewards, terms, truncs, infos = envs.step(env_actions)
            dones = np.logical_or(terms, truncs).astype(np.float32)

            step_data["is_first"] = np.zeros((args.num_envs, 1), np.float32)
            real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
            for i, info in enumerate(infos):
                if "final_observation" in info:
                    for k in obs_keys:
                        real_next_obs[k][i] = info["final_observation"][k]

            for k in obs_keys:
                step_data[k] = np.asarray(next_obs[k])
            obs = next_obs
            step_data["dones"] = dones[:, None]
            step_data["rewards"] = (
                np.tanh(rewards)[:, None] if args.clip_rewards else rewards[:, None]
            ).astype(np.float32)

            dones_idxes = np.nonzero(dones)[0].tolist()
            if dones_idxes:
                n_reset = len(dones_idxes)
                reset_data = {
                    k: real_next_obs[k][dones_idxes][None] for k in obs_keys
                }
                reset_data["dones"] = np.ones((1, n_reset, 1), np.float32)
                reset_data["actions"] = np.zeros((1, n_reset, act_sum), np.float32)
                reset_data["rewards"] = step_data["rewards"][dones_idxes][None]
                reset_data["is_first"] = np.zeros((1, n_reset, 1), np.float32)
                ops.append((reset_data, dones_idxes))
                step_data["rewards"][dones_idxes] = 0.0
                step_data["dones"][dones_idxes] = 0.0
                step_data["is_first"][dones_idxes] = 1.0
                if not link.random_phase:
                    reset_mask = np.zeros((args.num_envs,), np.float32)
                    reset_mask[dones_idxes] = 1.0
                    player_state = player.reset_states(
                        player_state, jnp.asarray(reset_mask)
                    )
            env_steps += args.num_envs

            if rows_pending >= PUSH_EVERY_ROWS:
                # dv3 buffers rows across steps, so the push span alone is
                # the provenance unit (no per-chunk collect window exists)
                push = tracer.begin(
                    "push", parent=fetcher.last_span, actor=actor_id
                )
                link.push(
                    ops,
                    rows=rows_pending,
                    env_steps=env_steps,
                    weight_version=version,
                    trace=_push_trace(push, actor_id),
                )
                tracer.end(push, rows=rows_pending, weight_version=version)
                ops, rows_pending = [], 0
            link.maybe_heartbeat(env_steps, version)
    finally:
        fetcher.stop()
        link.close()
        envs.close()


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def main() -> int:
    addr = os.environ["SHEEPRL_TPU_FLOCK_ADDR"]
    actor_id = int(os.environ["SHEEPRL_TPU_FLOCK_ACTOR_ID"])
    algo = os.environ["SHEEPRL_TPU_FLOCK_ALGO"]
    cfg = json.loads(os.environ["SHEEPRL_TPU_FLOCK_ARGS"])
    log_dir = os.environ.get("SHEEPRL_TPU_FLOCK_LOG_DIR") or tempfile.mkdtemp(
        prefix="flock-actor-"
    )
    if algo == "ppo":
        from ..algos.ppo.args import PPOArgs
        from ..utils.parser import DataclassArgumentParser

        (args,) = DataclassArgumentParser(PPOArgs).parse_dict(cfg)
        runner = run_ppo
    elif algo == "dreamer_v3":
        from ..algos.dreamer_v3.args import DreamerV3Args
        from ..utils.parser import DataclassArgumentParser

        (args,) = DataclassArgumentParser(DreamerV3Args).parse_dict(cfg)
        runner = run_dreamer_v3
    else:
        print(f"flock actor: unsupported algo {algo!r}", file=sys.stderr)
        return 2
    from ..telemetry.core import Telemetry
    from ..telemetry.trace import install_profile_signal

    # the sheepscope per-role shard: telemetry.actor{N}.jsonl in the SHARED
    # run directory (SHEEPRL_TPU_FLOCK_LOG_DIR), run id inherited from the
    # launcher's environment
    telem = Telemetry.from_args(
        args, log_dir, 0, algo=algo, role=f"actor{actor_id}"
    )
    install_profile_signal(log_dir)
    try:
        runner(args, actor_id, addr, log_dir, telem=telem)
    except (ConnectionError, wire.FrameError, TimeoutError):
        # the learner finished (service closed) or went away: a clean exit,
        # not a failure — the launcher treats rc 0 as "no respawn needed"
        return 0
    finally:
        telem.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
