"""Flock socket protocol: length-prefixed frames over localhost TCP or a
Unix-domain socket. Pickle-free end to end — control payloads are JSON,
bulk payloads are `data/wire.py` trees (width-class packed, bit-exact).

Frame layout (little-endian):

    magic(4) = b"FLK1" | kind(1) | flags(1) | reserved(2) | length(8)
    payload[length]

Kinds (actor -> service unless noted):

    HELLO       JSON {actor_id, pid, role, proto}
    WELCOME     (service) JSON {actor_id, shard_capacity, weight_version,
                                random_phase, generation}
    PUSH        u32 n_ops, then per op: u32 meta_len | meta_json
                | u64 blob_len | pack_tree blob.
                op meta: {indices: [..]|null}; frame-level trailing JSON
                rides in the first op's meta: {rows, env_steps,
                weight_version, trace?} — `trace` is the optional
                sheepscope context {span, actor, mono_ts} (ISSUE 17)
    PUSH_OK     (service) JSON {rows_total, random_phase, weight_version}
    HEARTBEAT   JSON {actor_id, env_steps, weight_version, sps,
                      mono_ts?, wall_ts?} — monotonic + wall send stamps
                      (mono feeds cross-host-safe eviction ages, wall
                      feeds the NTP-style clock-offset estimate)
    HEARTBEAT_OK(service) JSON {random_phase, weight_version,
                                server_wall_ts?}
    GET_WEIGHTS JSON {have_version}
    WEIGHTS     (service) u32 meta_len | {version, span?} | pack_leaves
                blob — span = the publish span id, parenting the actor's
                next collect span
    WEIGHTS_UNCHANGED (service) JSON {version}
    BYE         JSON {actor_id}
    ERROR       (either) JSON {error}
    PROFILE     (either direction) JSON {seconds?, dir?}; reply PROFILE
                JSON {ok, dir?, seconds?, error?, pid} — bounded
                on-demand jax.profiler window (sheepscope)

All sheepscope additions are OPTIONAL JSON keys or appended kinds: a peer
that predates them ignores unknown keys and never sends kind 17, so old
and new processes interoperate frame-for-frame.

Serving kinds (client -> server unless noted; sheeprl_tpu/serve/):

    REQUEST     u32 meta_len | meta_json | pack_tree obs blob.
                meta: {id, deadline_ms, session, reset, span?} — span =
                the client-side sheepscope span id, parenting the
                server's request span
    RESPONSE    (server) u32 meta_len | meta_json | pack_tree action blob.
                meta: {id, version, rung, rows, queue_ms, span?} — span =
                the server's request span id, echoed for client-side
                correlation
    SHED        (server) JSON {id, retry_after_ms, reason} — deadline-aware
                load shedding, NOT an error: retry after the hint
    RELOAD      JSON {path}; server replies RELOAD JSON
                {ok, version, error}

Scale-out kinds (ISSUE 19, flock/shm.py + flock/relay.py):

    SHM_ATTACH  JSON {actor_id, name, slots, slot_bytes} — the actor
                created a shared-memory ring (flock/shm.py) and asks the
                colocated service to drain it; reply SHM_ATTACH JSON
                {ok, error?}. After an ok the data socket carries only
                control frames (heartbeats, BYE) — PUSH payloads ride
                the ring
    RELAY_HELLO JSON {relay_id, pid, proto} — a relay (flock/relay.py)
                opens its upstream connection; reply WELCOME JSON
                {shard_capacity, weight_version, random_phase}
    PUSH_BATCH  u32 n_items, then per item u32 actor_id | u64 len |
                PUSH payload. One learner-side reply PUSH_OK JSON
                {rows_total, random_phase, weight_version} covers the
                whole batch
    RELAY_FWD   u32 actor_id | u8 inner_kind | inner payload — a
                downstream actor's control frame (HELLO/HEARTBEAT/BYE)
                forwarded verbatim through the relay; the reply is a
                RELAY_FWD wrapping the service's normal reply frame

Frame kinds form an EXTENSIBLE registry: subsystems claim values through
`register_kind` (u8, append-only — committed values are pinned by
tests/test_flock/test_wire.py and must never be renumbered; 1-11 belong
to flock, 12-16 to serve, 17 to sheepscope profiling, 18-21 to the
flock scale-out tier, 22+ are free).

Transport addresses serialize as `tcp:HOST:PORT` or `unix:PATH` — one
string, environment-variable friendly for actor subprocesses.

Network fault injection (ISSUE 16): the sheepfault `net.*` sites live HERE,
in the one framing layer every distributed tier shares, so one injection
point covers flock actors, the replay service, serve clients and the serve
server alike. With no fault clauses armed the hook is a single attribute
read on the process-global plan — the frame path stays byte-identical.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

__all__ = [
    "CORRUPT_MAGIC",
    "MAGIC",
    "MAX_FRAME_BYTES",
    "FrameError",
    "KIND_NAMES",
    "connect",
    "format_address",
    "inject_shm_send",
    "open_partition_window",
    "pack_push_batch",
    "pack_relay_fwd",
    "unpack_push_batch",
    "unpack_relay_fwd",
    "parse_address",
    "recv_frame",
    "recv_json",
    "register_kind",
    "send_frame",
    "send_json",
]

MAGIC = b"FLK1"
# what `net.corrupt` overwrites the magic with: same length as MAGIC, can
# never collide with a valid header, and greps memorably in packet dumps
CORRUPT_MAGIC = b"XXXX"
_HEADER = struct.Struct("<4sBBHQ")
# a pushed chunk is rollout-sized, weights are model-sized; 1 GiB is far
# above both and guards against a corrupt length field allocating the moon
MAX_FRAME_BYTES = 1 << 30

# value -> wire name for every registered frame kind (diagnostics only —
# the VALUE is the protocol)
KIND_NAMES: dict[int, str] = {}


def register_kind(value: int, name: str) -> int:
    """Claim a frame-kind value in the shared FLK1 registry. Kinds are a
    single u8 on the wire, so the registry enforces the two corruptions a
    closed constant set silently allowed: a value collision between two
    subsystems, and an out-of-range value truncated by the header pack.
    Returns `value` so kinds read as constants at the definition site."""
    if not 1 <= value <= 255:
        raise ValueError(f"frame kind {value} out of u8 range [1, 255]")
    if value in KIND_NAMES and KIND_NAMES[value] != name:
        raise ValueError(
            f"frame kind {value} already registered as {KIND_NAMES[value]!r} "
            f"(attempted {name!r})"
        )
    other = {v for v, n in KIND_NAMES.items() if n == name and v != value}
    if other:
        raise ValueError(
            f"frame-kind name {name!r} already registered as value {other}"
        )
    KIND_NAMES[value] = name
    return value


# flock kinds (PR 14, committed values — never renumber)
HELLO = register_kind(1, "hello")
WELCOME = register_kind(2, "welcome")
PUSH = register_kind(3, "push")
PUSH_OK = register_kind(4, "push_ok")
HEARTBEAT = register_kind(5, "heartbeat")
HEARTBEAT_OK = register_kind(6, "heartbeat_ok")
GET_WEIGHTS = register_kind(7, "get_weights")
WEIGHTS = register_kind(8, "weights")
WEIGHTS_UNCHANGED = register_kind(9, "weights_unchanged")
BYE = register_kind(10, "bye")
ERROR = register_kind(11, "error")

# serving kinds (PR 15, sheeprl_tpu/serve/ — appended, nothing renumbered)
REQUEST = register_kind(12, "request")
RESPONSE = register_kind(13, "response")
SHED = register_kind(14, "shed")
RELOAD = register_kind(15, "reload")

# 16 = "health" is claimed by sheeprl_tpu/serve/server.py at import time.

# sheepscope (ISSUE 17): open a bounded jax.profiler.trace window on any
# live process. JSON {seconds?, dir?}; the peer replies PROFILE JSON
# {ok, dir?, seconds?, error?, pid}. Registered HERE (not in telemetry/)
# because the registry is the wire module's and telemetry must stay
# importable without the flock package.
PROFILE = register_kind(17, "profile")

# flock scale-out tier (ISSUE 19): shared-memory transport + relay
# aggregation. Appended, nothing renumbered.
SHM_ATTACH = register_kind(18, "shm_attach")
RELAY_HELLO = register_kind(19, "relay_hello")
PUSH_BATCH = register_kind(20, "push_batch")
RELAY_FWD = register_kind(21, "relay_fwd")


class FrameError(ConnectionError):
    """Malformed frame or protocol violation on a flock socket."""


# ---------------------------------------------------------------------------
# relay codecs (ISSUE 19): payload layouts for RELAY_FWD / PUSH_BATCH.
# They live HERE — next to the kinds they encode — so flock/relay.py and
# flock/service.py share one definition without importing each other.
# ---------------------------------------------------------------------------

_FWD_HEAD = struct.Struct("<IB")
_U32S = struct.Struct("<I")
_U64S = struct.Struct("<Q")


def pack_relay_fwd(actor_id: int, inner_kind: int, payload: bytes = b"") -> bytes:
    """RELAY_FWD payload: u32 actor_id | u8 inner_kind | inner payload."""
    return _FWD_HEAD.pack(actor_id, inner_kind) + payload


def unpack_relay_fwd(payload: bytes) -> tuple[int, int, bytes]:
    actor_id, inner_kind = _FWD_HEAD.unpack_from(payload, 0)
    return actor_id, inner_kind, payload[_FWD_HEAD.size :]


def pack_push_batch(items) -> bytes:
    """PUSH_BATCH payload: u32 n, then per item u32 actor_id | u64 len |
    PUSH payload (the `service.pack_push` bytes, forwarded verbatim so
    sheepscope trace context survives the relay hop bit-for-bit)."""
    parts = [_U32S.pack(len(items))]
    for actor_id, payload in items:
        parts += [_U32S.pack(actor_id), _U64S.pack(len(payload)), payload]
    return b"".join(parts)


def unpack_push_batch(payload: bytes):
    try:
        (n,) = _U32S.unpack_from(payload, 0)
        off = 4
        items = []
        for _ in range(n):
            (actor_id,) = _U32S.unpack_from(payload, off)
            (plen,) = _U64S.unpack_from(payload, off + 4)
            off += 12
            if off + plen > len(payload):
                raise FrameError(
                    f"push_batch item overruns payload "
                    f"({off + plen} > {len(payload)})"
                )
            items.append((actor_id, payload[off : off + plen]))
            off += plen
    except struct.error as err:
        raise FrameError(f"truncated push_batch payload: {err}") from err
    if off != len(payload):
        raise FrameError(
            f"push_batch trailing bytes ({len(payload) - off} past item {n})"
        )
    return items


# ---------------------------------------------------------------------------
# injected network faults (resilience/inject.py `net.*` sites)
# ---------------------------------------------------------------------------

NET_SITES = ("net.drop", "net.delay", "net.corrupt", "net.partition")
DEFAULT_DELAY_MS = 100.0
DEFAULT_PARTITION_S = 2.0

# monotonic deadline of the open partition window: while it is in the
# future, `connect` from THIS process is refused — reconnect backoff has to
# wait the partition out instead of healing on its first retry
_partition_until = 0.0
_partition_gate = threading.Lock()


def partition_remaining() -> float:
    """Seconds left in the injected partition window (0.0 when closed)."""
    with _partition_gate:
        return max(0.0, _partition_until - time.monotonic())


def _fire_net_sites():
    """Advance every net site's per-process frame counter and return the
    specs that fired on this frame (usually none). Inert without an armed
    plan: one attribute read, no counters, no locks. Shared by the socket
    send path and the shm ring producer so `net.*` clauses fire no matter
    which transport carries the frame."""
    from ..resilience import inject

    plan = inject.get_plan()
    if not plan.specs or not any(s.site in NET_SITES for s in plan.pending()):
        return ()
    fired = []
    for site in NET_SITES:
        spec = plan.fire_next(site)
        if spec is not None:
            fired.append(spec)
            inject.count(f"Fault/{site}")
    return fired


def open_partition_window(seconds: float | None) -> None:
    """Open the process-local injected-partition window: `connect` refuses
    until it elapses, so reconnect backoff genuinely waits it out."""
    global _partition_until
    with _partition_gate:
        _partition_until = time.monotonic() + (
            seconds or DEFAULT_PARTITION_S
        )


def inject_shm_send(data: bytes) -> bytes | None:
    """`net.*` fault hook for the shared-memory ring producer
    (flock/shm.py), mapping each socket fault onto its shm analogue:
    delay sleeps before the slot write, drop returns None (the frame is
    never committed), corrupt garbles the payload AFTER its checksum was
    taken (the reader's CRC check skips the slot), and partition opens
    the connect-refusing window and raises — the link tears down the
    ring and falls back to the socket path, whose reconnect backoff then
    waits the window out."""
    for spec in _fire_net_sites():
        if spec.site == "net.delay":
            time.sleep((spec.param or DEFAULT_DELAY_MS) / 1000.0)
        elif spec.site == "net.drop":
            return None
        elif spec.site == "net.corrupt":
            data = CORRUPT_MAGIC + data[len(CORRUPT_MAGIC):]
        elif spec.site == "net.partition":
            open_partition_window(spec.param)
            raise ConnectionResetError(
                "injected net.partition: shm ring detached"
            )
    return data


def _inject_send(sock: socket.socket, data: bytes) -> bytes | None:
    """Apply any fired net fault to one socket frame. Returns the
    (possibly corrupted) bytes to send, or None when the frame must be
    silently dropped."""
    for spec in _fire_net_sites():
        if spec.site == "net.delay":
            time.sleep((spec.param or DEFAULT_DELAY_MS) / 1000.0)
        elif spec.site == "net.drop":
            return None
        elif spec.site == "net.corrupt":
            # garbled magic: the RECEIVER raises FrameError and kills that
            # one connection; the sender's socket stays healthy
            return CORRUPT_MAGIC + data[len(MAGIC):]
        elif spec.site == "net.partition":
            open_partition_window(spec.param)
            try:
                sock.shutdown(socket.SHUT_RDWR)  # both directions dead
            except OSError:
                pass
            raise ConnectionResetError(
                "injected net.partition: connection shut down both ways"
            )
    return data


def send_frame(sock: socket.socket, kind: int, payload: bytes = b"") -> None:
    data = _inject_send(
        sock, _HEADER.pack(MAGIC, kind, 0, 0, len(payload)) + payload
    )
    if data is None:
        return
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    """Exactly n bytes, or None on clean EOF at a frame boundary."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise FrameError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> tuple[int, bytes] | None:
    """-> (kind, payload), or None on clean EOF (peer went away)."""
    header = _recv_exact(sock, _HEADER.size)
    if header is None:
        return None
    magic, kind, _flags, _rsvd, length = _HEADER.unpack(header)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic!r} (expected {MAGIC!r})")
    if length > MAX_FRAME_BYTES:
        raise FrameError(f"frame length {length} exceeds cap")
    payload = _recv_exact(sock, length) if length else b""
    if length and payload is None:
        raise FrameError("connection closed before frame payload")
    return kind, payload or b""


def send_json(sock: socket.socket, kind: int, obj: dict) -> None:
    send_frame(sock, kind, json.dumps(obj).encode())


def recv_json(sock: socket.socket, expected_kind: int) -> dict:
    frame = recv_frame(sock)
    if frame is None:
        raise FrameError("connection closed awaiting reply")
    kind, payload = frame
    if kind == ERROR:
        raise FrameError(
            f"peer error: {json.loads(payload.decode()).get('error')}"
        )
    if kind != expected_kind:
        raise FrameError(
            f"expected {KIND_NAMES.get(expected_kind)}, got {KIND_NAMES.get(kind, kind)}"
        )
    return json.loads(payload.decode())


# ---------------------------------------------------------------------------
# addresses
# ---------------------------------------------------------------------------


def format_address(kind: str, *parts) -> str:
    if kind == "tcp":
        host, port = parts
        return f"tcp:{host}:{port}"
    if kind == "unix":
        (path,) = parts
        return f"unix:{path}"
    raise ValueError(f"unknown transport {kind!r}")


def parse_address(addr: str):
    """-> ('tcp', host, port) | ('unix', path)."""
    if addr.startswith("tcp:"):
        host, _, port = addr[4:].rpartition(":")
        return ("tcp", host, int(port))
    if addr.startswith("unix:"):
        return ("unix", addr[5:])
    raise ValueError(f"unparseable flock address {addr!r}")


def connect(addr: str, timeout: float | None = None) -> socket.socket:
    left = partition_remaining()
    if left > 0.0:
        raise ConnectionRefusedError(
            f"injected net.partition: {left:.2f}s left in the window"
        )
    parsed = parse_address(addr)
    if parsed[0] == "tcp":
        sock = socket.create_connection(parsed[1:], timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(parsed[1])
    return sock
