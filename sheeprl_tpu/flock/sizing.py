"""Per-actor replay shard capacities derived from the committed sheepmem
ledger (`analysis/budget/<spec>.json`, the PR-10 static memory analysis).

Policy: the host-side replay tier for a flock run gets a byte budget that
scales with the task's measured device working set — the ledger's largest
`peak_bytes` entry (in practice the train step) times a host multiplier —
so a task whose update footprint grew (bigger models, longer sequences)
automatically gets a proportionally deeper replay tier, and the number is
a MEASURED artifact of the committed ledger rather than a magic constant.
The budget is split evenly across actors and converted to rows through
the actual packed row width (`data.wire.tree_nbytes` of one row-tree).

Environment overrides:

    SHEEPRL_TPU_FLOCK_SHARD_BYTES    total byte budget across all shards
                                     (wins over the ledger)
    SHEEPRL_TPU_FLOCK_HOST_FACTOR    ledger peak -> host budget multiplier
                                     (default 64: host RAM is plentiful
                                     next to HBM)

Everything here is deterministic: same ledger + same env -> same
capacities, so two runs of the same spec shard identically.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

__all__ = ["ledger_peak_bytes", "shard_capacity"]

# repo root: sheeprl_tpu/flock/sizing.py -> sheeprl_tpu/flock -> sheeprl_tpu -> repo
_REPO = Path(__file__).resolve().parents[2]
_BUDGET_DIR = _REPO / "analysis" / "budget"

_DEFAULT_HOST_FACTOR = 64
# never size a shard below something trainable, never above a cap that
# would dwarf the in-process defaults
_FLOOR_ROWS = 64
_CEIL_ROWS = 1_000_000


def ledger_peak_bytes(spec: str) -> int | None:
    """Largest `peak_bytes` in `analysis/budget/<spec>.json`'s memory
    section, or None when the spec has no committed ledger (new task,
    stripped checkout) — callers fall back to a fixed budget."""
    path = _BUDGET_DIR / f"{spec}.json"
    try:
        with open(path) as fh:
            ledger = json.load(fh)
    except (OSError, ValueError):
        return None
    peaks = [
        int(rec["peak_bytes"])
        for rec in ledger.get("memory", {}).values()
        if isinstance(rec, dict) and "peak_bytes" in rec
    ]
    return max(peaks) if peaks else None


def shard_capacity(
    spec: str,
    n_actors: int,
    row_nbytes: int,
    *,
    floor_rows: int = _FLOOR_ROWS,
    ceil_rows: int = _CEIL_ROWS,
    fallback_budget_bytes: int = 256 * 1024 * 1024,
) -> int:
    """Rows per actor shard for `spec` split over `n_actors` actors.

    `row_nbytes` is the packed width of ONE buffer row (one env-step across
    the actor's envs) — compute it with `data.wire.tree_nbytes` on a real
    row-tree so dtype/shape changes reprice the shard automatically.
    """
    if n_actors <= 0:
        raise ValueError(f"n_actors must be positive, got {n_actors}")
    if row_nbytes <= 0:
        raise ValueError(f"row_nbytes must be positive, got {row_nbytes}")
    override = os.environ.get("SHEEPRL_TPU_FLOCK_SHARD_BYTES")
    if override:
        total = int(override)
    else:
        peak = ledger_peak_bytes(spec)
        if peak is None:
            total = fallback_budget_bytes
        else:
            factor = int(
                os.environ.get(
                    "SHEEPRL_TPU_FLOCK_HOST_FACTOR", _DEFAULT_HOST_FACTOR
                )
            )
            total = peak * factor
    rows = total // (n_actors * row_nbytes)
    return int(min(max(rows, floor_rows), ceil_rows))
