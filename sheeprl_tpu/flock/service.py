"""Learner-side replay service: the socket half of the Sebulba split.

Runs INSIDE the learner process (an accept thread plus one handler thread
per actor connection) and hosts one replay shard per actor — an ordinary
`data/buffers.py` buffer, so the learner samples with plain function
calls: there is NO socket on the sample path. Actors connect over the
`flock/wire.py` frame protocol, register (HELLO/WELCOME), stream rollout
ops (PUSH), heartbeat, and pull versioned weight snapshots on a second
connection so the fetch never blocks their env-step loop.

Two shard modes cover the two algorithm families:

    mode="chunks"  on-policy (ppo): each PUSH carries one whole rollout
                   chunk; the service keeps a bounded per-actor queue and
                   the learner drains round-robin with `next_chunk()`.
                   A full queue drops the OLDEST chunk (on-policy data
                   ages out; `Flock/chunks_dropped` counts the loss).
    mode="buffer"  off-policy (dreamer_v3): each PUSH carries ordered
                   buffer ops `(row_tree, indices|None)` applied to the
                   actor's shard via its normal `.add()`; the learner
                   calls `sample()` which partitions the batch across
                   filled shards and concatenates.

Membership is elastic: a dead connection only marks the actor
disconnected (its shard stays sampleable), and a reconnecting actor with
the same id bumps its generation and resumes filling the same shard —
the `flock.actor_rejoined` event is the receipt the CI fault-smoke
scenario asserts on.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from ..telemetry import core as telemetry
from . import wire

__all__ = ["ReplayService"]

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

PROTO_VERSION = 1


def pack_push(ops, *, rows: int, env_steps: int, weight_version: int) -> bytes:
    """PUSH payload: u32 n_ops, then per op u32 meta_len | meta_json |
    u64 blob_len | pack_tree blob. Frame-level stats ride in op 0's meta.
    `ops` is a list of (host_tree, indices|None)."""
    from ..data.wire import pack_tree

    parts = [_U32.pack(len(ops))]
    for i, (tree, indices) in enumerate(ops):
        meta: dict[str, Any] = {
            "indices": None if indices is None else [int(j) for j in indices]
        }
        if i == 0:
            meta.update(
                rows=int(rows),
                env_steps=int(env_steps),
                weight_version=int(weight_version),
            )
        blob = pack_tree(tree)
        mb = json.dumps(meta).encode()
        parts += [_U32.pack(len(mb)), mb, _U64.pack(len(blob)), blob]
    return b"".join(parts)


def unpack_push(payload: bytes):
    """-> (ops, frame_meta) where ops = [(tree, indices|None), ...]."""
    from ..data.wire import unpack_tree

    (n_ops,) = _U32.unpack_from(payload, 0)
    off = 4
    ops = []
    frame_meta: dict[str, Any] = {}
    for i in range(n_ops):
        (meta_len,) = _U32.unpack_from(payload, off)
        off += 4
        meta = json.loads(payload[off : off + meta_len].decode())
        off += meta_len
        (blob_len,) = _U64.unpack_from(payload, off)
        off += 8
        tree = unpack_tree(payload[off : off + blob_len])
        off += blob_len
        if i == 0:
            frame_meta = {
                k: meta.get(k) for k in ("rows", "env_steps", "weight_version")
            }
        ops.append((tree, meta.get("indices")))
    return ops, frame_meta


class _ActorState:
    __slots__ = (
        "actor_id",
        "generation",
        "connected",
        "ever_connected",
        "pid",
        "last_heartbeat",
        "env_steps",
        "weight_version",
        "sps",
        "rows",
    )

    def __init__(self, actor_id: int):
        self.actor_id = actor_id
        self.generation = 0
        self.connected = False
        self.ever_connected = False
        self.pid = -1
        self.last_heartbeat = 0.0
        self.env_steps = 0
        self.weight_version = -1
        self.sps = 0.0
        self.rows = 0


class ReplayService:
    """Sharded replay + membership + weight distribution for one learner."""

    def __init__(
        self,
        *,
        algo: str,
        n_actors: int,
        mode: str,
        capacity_rows: int,
        make_shard: Callable[[int], Any] | None = None,
        transport: str | None = None,
        telem: "telemetry.Telemetry | None" = None,
    ):
        if mode not in ("chunks", "buffer"):
            raise ValueError(f"mode must be 'chunks' or 'buffer', got {mode!r}")
        if mode == "buffer" and make_shard is None:
            raise ValueError("buffer mode needs a make_shard factory")
        self.algo = algo
        self.n_actors = n_actors
        self.mode = mode
        self.capacity_rows = capacity_rows
        self._telem = telem
        self._lock = threading.RLock()
        self._chunk_ready = threading.Condition(self._lock)
        self._membership = threading.Condition(self._lock)
        self._actors = {i: _ActorState(i) for i in range(n_actors)}
        # shards outlive connections: a rejoining actor resumes filling its own
        self._shards = (
            {i: make_shard(capacity_rows) for i in range(n_actors)}
            if mode == "buffer"
            else {}
        )
        self._shard_locks = {i: threading.Lock() for i in range(n_actors)}
        self._chunks: dict[int, deque] = {i: deque() for i in range(n_actors)}
        self._chunk_cap: dict[int, int] = {}
        self._drain_order = 0
        self._weight_version = 0
        self._weight_payload: bytes | None = None
        self._publish_ts: dict[int, float] = {}
        self._random_phase = False
        self._rows_total = 0
        self._chunks_dropped = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._listener: socket.socket | None = None
        self._unix_path: str | None = None
        self.address = ""
        self._transport = transport or os.environ.get(
            "SHEEPRL_TPU_FLOCK_TRANSPORT", "unix"
        )

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> str:
        if self._transport == "tcp":
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", 0))
            self.address = wire.format_address(
                "tcp", "127.0.0.1", srv.getsockname()[1]
            )
        else:
            # a short tempdir path: AF_UNIX paths cap at ~107 bytes
            sock_dir = tempfile.mkdtemp(prefix="flock-")
            self._unix_path = os.path.join(sock_dir, "svc.sock")
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(self._unix_path)
            self.address = wire.format_address("unix", self._unix_path)
        srv.listen(self.n_actors * 2 + 2)
        self._listener = srv
        t = threading.Thread(
            target=self._accept_loop, name="flock-accept", daemon=True
        )
        t.start()
        self._threads.append(t)
        self._event("flock.started", address=self.address, mode=self.mode)
        return self.address

    def close(self) -> None:
        self._stop.set()
        for sock in [self._listener, *self._conns]:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        for t in self._threads:
            t.join(timeout=2.0)
        if self._unix_path:
            try:
                os.unlink(self._unix_path)
                os.rmdir(os.path.dirname(self._unix_path))
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- socket side ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(
                target=self._serve, args=(conn,), name="flock-conn", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        actor_id = None
        role = "data"
        try:
            frame = wire.recv_frame(conn)
            if frame is None or frame[0] != wire.HELLO:
                return
            hello = json.loads(frame[1].decode())
            actor_id = int(hello["actor_id"])
            role = hello.get("role", "data")
            if actor_id not in self._actors or hello.get("proto") != PROTO_VERSION:
                wire.send_json(
                    conn, wire.ERROR, {"error": f"bad hello {hello!r}"}
                )
                return
            if role == "weights":
                self._serve_weights(conn)
                return
            self._register(actor_id, hello)
            wire.send_json(
                conn,
                wire.WELCOME,
                {
                    "actor_id": actor_id,
                    "shard_capacity": self.capacity_rows,
                    "weight_version": self._weight_version,
                    "random_phase": self._random_phase,
                    "generation": self._actors[actor_id].generation,
                },
            )
            while not self._stop.is_set():
                frame = wire.recv_frame(conn)
                if frame is None:
                    break
                kind, payload = frame
                if kind == wire.PUSH:
                    self._handle_push(conn, actor_id, payload)
                elif kind == wire.HEARTBEAT:
                    self._handle_heartbeat(conn, actor_id, payload)
                elif kind == wire.BYE:
                    break
                else:
                    wire.send_json(
                        conn,
                        wire.ERROR,
                        {"error": f"unexpected {wire.KIND_NAMES.get(kind, kind)}"},
                    )
        except (wire.FrameError, OSError, ValueError, KeyError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if actor_id in self._actors and role == "data":
                self._deregister(actor_id)

    def _serve_weights(self, conn: socket.socket) -> None:
        """Dedicated weight-pull connection: GET_WEIGHTS request/reply only,
        so a slow snapshot transfer never sits between two PUSHes."""
        while not self._stop.is_set():
            frame = wire.recv_frame(conn)
            if frame is None:
                return
            kind, payload = frame
            if kind != wire.GET_WEIGHTS:
                wire.send_json(conn, wire.ERROR, {"error": "weights conn"})
                return
            have = json.loads(payload.decode()).get("have_version", -1)
            with self._lock:
                version, blob = self._weight_version, self._weight_payload
            if blob is None or have == version:
                wire.send_json(conn, wire.WEIGHTS_UNCHANGED, {"version": version})
            else:
                wire.send_frame(conn, wire.WEIGHTS, blob)

    def _register(self, actor_id: int, hello: dict) -> None:
        with self._lock:
            st = self._actors[actor_id]
            rejoin = st.ever_connected
            st.generation += 1 if rejoin else 0
            st.connected = True
            st.ever_connected = True
            st.pid = int(hello.get("pid", -1))
            st.last_heartbeat = time.monotonic()
            self._membership.notify_all()
        if rejoin:
            self._event(
                "flock.actor_rejoined",
                actor_id=actor_id,
                generation=st.generation,
                weight_version=self._weight_version,
            )
        else:
            self._event("flock.actor_joined", actor_id=actor_id, pid=st.pid)

    def _deregister(self, actor_id: int) -> None:
        with self._lock:
            st = self._actors[actor_id]
            was = st.connected
            st.connected = False
        if was:
            self._event(
                "flock.actor_disconnected",
                actor_id=actor_id,
                rows=st.rows,
                env_steps=st.env_steps,
            )

    def _handle_push(self, conn, actor_id: int, payload: bytes) -> None:
        ops, meta = unpack_push(payload)
        rows = int(meta.get("rows") or 0)
        if self.mode == "buffer":
            shard = self._shards[actor_id]
            with self._shard_locks[actor_id]:
                for tree, indices in ops:
                    shard.add(tree, indices=indices)
        else:
            with self._lock:
                q = self._chunks[actor_id]
                cap = self._chunk_cap.get(actor_id)
                if cap is None and rows:
                    cap = max(2, self.capacity_rows // rows)
                    self._chunk_cap[actor_id] = cap
                if cap and len(q) >= cap:
                    q.popleft()
                    self._chunks_dropped += 1
                for tree, _ in ops:
                    q.append(tree)
                self._chunk_ready.notify_all()
        with self._lock:
            st = self._actors[actor_id]
            st.rows += rows
            st.env_steps = int(meta.get("env_steps") or st.env_steps)
            st.weight_version = int(
                meta.get("weight_version", st.weight_version)
            )
            st.last_heartbeat = time.monotonic()
            self._rows_total += rows
            reply = {
                "rows_total": self._rows_total,
                "random_phase": self._random_phase,
                "weight_version": self._weight_version,
            }
        wire.send_json(conn, wire.PUSH_OK, reply)

    def _handle_heartbeat(self, conn, actor_id: int, payload: bytes) -> None:
        hb = json.loads(payload.decode())
        with self._lock:
            st = self._actors[actor_id]
            st.last_heartbeat = time.monotonic()
            st.env_steps = int(hb.get("env_steps", st.env_steps))
            st.weight_version = int(hb.get("weight_version", st.weight_version))
            st.sps = float(hb.get("sps", st.sps))
            reply = {
                "random_phase": self._random_phase,
                "weight_version": self._weight_version,
            }
        wire.send_json(conn, wire.HEARTBEAT_OK, reply)

    # -- learner side ---------------------------------------------------------

    def publish(self, leaves) -> int:
        """Snapshot a new weight version from flattened model leaves. The
        device->host pull and the byte packing happen ONCE here; every
        actor pull then reuses the cached frame."""
        from ..data.wire import pack_leaves

        host_leaves = [np.asarray(leaf) for leaf in leaves]
        blob = pack_leaves(host_leaves)
        with self._lock:
            self._weight_version += 1
            version = self._weight_version
            meta = json.dumps({"version": version}).encode()
            self._weight_payload = _U32.pack(len(meta)) + meta + blob
            self._publish_ts[version] = time.monotonic()
            # keep the timestamp map bounded
            for old in [v for v in self._publish_ts if v < version - 64]:
                del self._publish_ts[old]
        return version

    @property
    def weight_version(self) -> int:
        return self._weight_version

    def set_random_phase(self, flag: bool) -> None:
        with self._lock:
            self._random_phase = bool(flag)

    def wait_for_actors(self, n: int | None = None, timeout: float = 60.0) -> bool:
        """Block until n actors (default: all) have registered."""
        want = self.n_actors if n is None else n
        deadline = time.monotonic() + timeout
        with self._membership:
            while self.actors_alive() < want:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    return False
                self._membership.wait(timeout=min(left, 0.5))
        return True

    def actors_alive(self) -> int:
        return sum(1 for st in self._actors.values() if st.connected)

    def next_chunk(self, timeout: float | None = None):
        """Chunks mode: pop the next rollout chunk, round-robin across
        actors so one fast actor cannot starve the rest. None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._chunk_ready:
            while True:
                ids = sorted(self._chunks)
                for k in range(len(ids)):
                    aid = ids[(self._drain_order + k) % len(ids)]
                    if self._chunks[aid]:
                        self._drain_order = (ids.index(aid) + 1) % len(ids)
                        return self._chunks[aid].popleft()
                if self._stop.is_set():
                    return None
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return None
                self._chunk_ready.wait(timeout=0.5 if left is None else min(left, 0.5))

    def sample(self, batch_size: int, **kw):
        """Buffer mode: partition the batch across shards that can serve it
        and concatenate — local calls only, no socket. Shards still warming
        up (or disconnected mid-fill) are skipped; the batch re-partitions
        over the rest."""
        ready = sorted(self._shards)
        counts = [batch_size // len(ready)] * len(ready)
        for i in range(batch_size % len(ready)):
            counts[i] += 1
        parts, served, missing = [], [], 0
        for aid, n in zip(ready, counts):
            if n == 0:
                continue
            with self._shard_locks[aid]:
                try:
                    parts.append(self._shards[aid].sample(n, **kw))
                    served.append(aid)
                except (ValueError, RuntimeError):
                    missing += n
        if not parts:
            # the partition may have skipped (n == 0) the only shard with
            # data — e.g. batch_size < n_actors early in the run. Any single
            # shard that can serve the WHOLE batch keeps training moving.
            for aid in ready:
                with self._shard_locks[aid]:
                    try:
                        return self._shards[aid].sample(batch_size, **kw)
                    except (ValueError, RuntimeError):
                        continue
            raise RuntimeError("no flock shard could serve the sample request")
        if missing:
            # a shard still warming up drops out; its slice tops up from a
            # shard that CAN serve, so the batch shape never shrinks (the
            # train jit's aval is part of the warm-compile contract)
            aid = served[0]
            with self._shard_locks[aid]:
                parts.append(self._shards[aid].sample(missing, **kw))
        axis = 2 if "sequence_length" in kw else 0
        return {
            k: np.concatenate([p[k] for p in parts], axis=axis)
            for k in parts[0]
        }

    def rows_total(self) -> int:
        return self._rows_total

    def shard(self, actor_id: int):
        return self._shards.get(actor_id)

    # -- observability --------------------------------------------------------

    def gauges(self) -> dict[str, float]:
        now = time.monotonic()
        with self._lock:
            out: dict[str, float] = {
                "Flock/actors_alive": float(self.actors_alive()),
                "Flock/weight_version": float(self._weight_version),
                "Flock/rows_total": float(self._rows_total),
                "Flock/chunks_dropped": float(self._chunks_dropped),
            }
            for aid, st in self._actors.items():
                if not st.ever_connected:
                    continue
                prefix = f"Flock/actor{aid}"
                lag = max(0, self._weight_version - max(st.weight_version, 0))
                # staleness: how long ago the version this actor acts with
                # stopped being current (0 while it holds the latest)
                if lag == 0:
                    staleness = 0.0
                else:
                    superseded = self._publish_ts.get(
                        max(st.weight_version, 0) + 1
                    )
                    staleness = 0.0 if superseded is None else now - superseded
                if self.mode == "buffer":
                    fill = min(st.rows, self.capacity_rows) / max(
                        self.capacity_rows, 1
                    )
                else:
                    cap = self._chunk_cap.get(aid, 0)
                    fill = len(self._chunks[aid]) / cap if cap else 0.0
                out[f"{prefix}/env_steps_s"] = float(st.sps)
                out[f"{prefix}/env_steps"] = float(st.env_steps)
                out[f"{prefix}/weight_version"] = float(st.weight_version)
                out[f"{prefix}/version_lag"] = float(lag)
                out[f"{prefix}/staleness_s"] = float(staleness)
                out[f"{prefix}/shard_fill"] = float(fill)
                out[f"{prefix}/heartbeat_age_s"] = (
                    float(now - st.last_heartbeat) if st.last_heartbeat else -1.0
                )
                out[f"{prefix}/connected"] = float(st.connected)
                out[f"{prefix}/generation"] = float(st.generation)
        return out

    def _event(self, name: str, **data) -> None:
        if self._telem is not None:
            self._telem.event(name, **data)
        else:
            telemetry.emit(name, **data)
