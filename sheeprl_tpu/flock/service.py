"""Learner-side replay service: the socket half of the Sebulba split.

Runs INSIDE the learner process (an accept thread plus one handler thread
per actor connection) and hosts one replay shard per actor — an ordinary
`data/buffers.py` buffer, so the learner samples with plain function
calls: there is NO socket on the sample path. Actors connect over the
`flock/wire.py` frame protocol, register (HELLO/WELCOME), stream rollout
ops (PUSH), heartbeat, and pull versioned weight snapshots on a second
connection so the fetch never blocks their env-step loop.

Two shard modes cover the two algorithm families:

    mode="chunks"  on-policy (ppo): each PUSH carries one whole rollout
                   chunk; the service keeps a bounded per-actor queue and
                   the learner drains round-robin with `next_chunk()`.
                   A full queue drops the OLDEST chunk (on-policy data
                   ages out; `Flock/chunks_dropped` counts the loss).
    mode="buffer"  off-policy (dreamer_v3): each PUSH carries ordered
                   buffer ops `(row_tree, indices|None)` applied to the
                   actor's shard via its normal `.add()`; the learner
                   calls `sample()` which partitions the batch across
                   filled shards and concatenates.

Membership is elastic: a dead connection only marks the actor
disconnected (its shard stays sampleable), and a reconnecting actor with
the same id bumps its generation and resumes filling the same shard —
the `flock.actor_rejoined` event is the receipt the CI fault-smoke
scenario asserts on.

Scale-out (ISSUE 19): two transports besides the per-actor socket. A
colocated actor can attach a shared-memory ring (SHM_ATTACH ->
`flock/shm.py`); a per-ring `ShmReceiver` drain thread ingests the ring's
PUSH payloads through the same `_ingest_push` the socket path uses, so
shard contents are transport-independent byte for byte. A relay
(`flock/relay.py`) multiplexes many actors over ONE upstream connection:
RELAY_HELLO opens it, PUSH_BATCH carries batched PUSH payloads, and
RELAY_FWD forwards actor control frames (HELLO/HEARTBEAT/BYE) verbatim —
membership, generations and rejoin events behave exactly as if each
actor were directly connected. `Flock/transport/*` gauges count frames
and bytes per transport.

Crash-resume (ISSUE 16): `save_sidecar` snapshots the service next to a
learner checkpoint — shard contents via the buffers' own `to_bytes()`
wire codecs, the per-actor generation/weight-version table, and the bound
address — and `restore_sidecar` + `start()` rehosts the service at the
SAME address, so surviving actors reconnect (capped backoff on their
side), re-HELLO with a bumped generation, and no committed row is lost.
Actors whose heartbeat goes stale past
`SHEEPRL_TPU_FLOCK_HEARTBEAT_TIMEOUT_S` are evicted: the connection is
freed (the shard is kept for rejoin), `flock.actor_stale` is emitted, and
the optional `on_evict` callback lets ActorFleet apply its respawn
budget.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable

import numpy as np

from ..telemetry import core as telemetry
from . import wire

__all__ = ["ReplayService"]

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

PROTO_VERSION = 1

SIDECAR_MAGIC = b"SFLK"
SIDECAR_SUFFIX = ".flock"
HEARTBEAT_TIMEOUT_VAR = "SHEEPRL_TPU_FLOCK_HEARTBEAT_TIMEOUT_S"
DEFAULT_HEARTBEAT_TIMEOUT_S = 30.0


def pack_push(
    ops,
    *,
    rows: int,
    env_steps: int,
    weight_version: int,
    trace: dict | None = None,
) -> bytes:
    """PUSH payload: u32 n_ops, then per op u32 meta_len | meta_json |
    u64 blob_len | pack_tree blob. Frame-level stats ride in op 0's meta.
    `ops` is a list of (host_tree, indices|None). `trace` is the optional
    sheepscope context {span, actor, mono_ts} — absent entirely when
    tracing is off, so old receivers never see the key."""
    from ..data.wire import pack_tree

    parts = [_U32.pack(len(ops))]
    for i, (tree, indices) in enumerate(ops):
        meta: dict[str, Any] = {
            "indices": None if indices is None else [int(j) for j in indices]
        }
        if i == 0:
            meta.update(
                rows=int(rows),
                env_steps=int(env_steps),
                weight_version=int(weight_version),
            )
            if trace:
                meta["trace"] = trace
        blob = pack_tree(tree)
        mb = json.dumps(meta).encode()
        parts += [_U32.pack(len(mb)), mb, _U64.pack(len(blob)), blob]
    return b"".join(parts)


def unpack_push(payload: bytes):
    """-> (ops, frame_meta) where ops = [(tree, indices|None), ...].
    frame_meta carries a "trace" key only when the sender included one."""
    from ..data.wire import unpack_tree

    (n_ops,) = _U32.unpack_from(payload, 0)
    off = 4
    ops = []
    frame_meta: dict[str, Any] = {}
    for i in range(n_ops):
        (meta_len,) = _U32.unpack_from(payload, off)
        off += 4
        meta = json.loads(payload[off : off + meta_len].decode())
        off += meta_len
        (blob_len,) = _U64.unpack_from(payload, off)
        off += 8
        tree = unpack_tree(payload[off : off + blob_len])
        off += blob_len
        if i == 0:
            frame_meta = {
                k: meta.get(k) for k in ("rows", "env_steps", "weight_version")
            }
            if meta.get("trace"):
                frame_meta["trace"] = meta["trace"]
        ops.append((tree, meta.get("indices")))
    return ops, frame_meta


class _ActorState:
    __slots__ = (
        "actor_id",
        "generation",
        "connected",
        "ever_connected",
        "pid",
        "last_heartbeat",
        "env_steps",
        "weight_version",
        "sps",
        "rows",
        # sender-monotonic liveness (ISSUE 17 satellite): baselines pairing
        # the actor's OWN monotonic clock with ours, so staleness ages stop
        # comparing clocks across hosts
        "sender_mono0",
        "recv_mono0",
        "last_sender_mono",
    )

    def __init__(self, actor_id: int):
        self.actor_id = actor_id
        self.generation = 0
        self.connected = False
        self.ever_connected = False
        self.pid = -1
        self.last_heartbeat = 0.0
        self.env_steps = 0
        self.weight_version = -1
        self.sps = 0.0
        self.rows = 0
        self.sender_mono0 = None
        self.recv_mono0 = None
        self.last_sender_mono = None

    def note_sender_mono(self, mono_ts) -> None:
        """Record a frame's sender-side monotonic stamp. First stamp per
        connection generation (or a regression — the actor restarted and
        its monotonic clock reset) re-baselines the pair."""
        if mono_ts is None:
            return
        mono = float(mono_ts)
        if self.sender_mono0 is None or (
            self.last_sender_mono is not None and mono < self.last_sender_mono
        ):
            self.sender_mono0 = mono
            self.recv_mono0 = time.monotonic()
        self.last_sender_mono = mono

    def heartbeat_age(self, now: float) -> float:
        """Seconds since this actor last SENT anything, measured on the
        sender's monotonic clock when it provides stamps (cross-host safe:
        elapsed receiver time minus elapsed sender time = time the sender
        has been silent). Old peers without stamps fall back to the
        receiver-clock age."""
        if self.last_sender_mono is not None and self.sender_mono0 is not None:
            age = (now - self.recv_mono0) - (
                self.last_sender_mono - self.sender_mono0
            )
            return max(age, 0.0)
        return now - self.last_heartbeat


class ReplayService:
    """Sharded replay + membership + weight distribution for one learner."""

    def __init__(
        self,
        *,
        algo: str,
        n_actors: int,
        mode: str,
        capacity_rows: int,
        make_shard: Callable[[int], Any] | None = None,
        transport: str | None = None,
        telem: "telemetry.Telemetry | None" = None,
    ):
        if mode not in ("chunks", "buffer"):
            raise ValueError(f"mode must be 'chunks' or 'buffer', got {mode!r}")
        if mode == "buffer" and make_shard is None:
            raise ValueError("buffer mode needs a make_shard factory")
        self.algo = algo
        self.n_actors = n_actors
        self.mode = mode
        self.capacity_rows = capacity_rows
        self._telem = telem
        self._lock = threading.RLock()
        self._chunk_ready = threading.Condition(self._lock)
        self._membership = threading.Condition(self._lock)
        self._actors = {i: _ActorState(i) for i in range(n_actors)}
        # shards outlive connections: a rejoining actor resumes filling its own
        self._shards = (
            {i: make_shard(capacity_rows) for i in range(n_actors)}
            if mode == "buffer"
            else {}
        )
        self._shard_locks = {i: threading.Lock() for i in range(n_actors)}
        self._chunks: dict[int, deque] = {i: deque() for i in range(n_actors)}
        self._chunk_cap: dict[int, int] = {}
        self._drain_order = 0
        # fair remainder rotation for sample() partitioning (ISSUE 19
        # satellite): part of the sample state so the assembler's rewind
        # and the crash-resume sidecar both preserve it
        self._sample_rr = 0
        # per-transport ingest counters behind the Flock/transport/* gauges
        self._tx: dict[str, int] = {
            "socket_frames": 0, "socket_bytes": 0,
            "shm_frames": 0, "shm_bytes": 0, "shm_corrupt": 0,
            "relay_frames": 0, "relay_bytes": 0, "relay_batches": 0,
        }
        # actor_id -> live ShmReceiver drain thread (ISSUE 19)
        self._shm_rx: dict[int, Any] = {}
        self._weight_version = 0
        self._weight_payload: bytes | None = None
        self._publish_ts: dict[int, float] = {}
        self._random_phase = False
        self._rows_total = 0
        self._chunks_dropped = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._data_conns: dict[int, socket.socket] = {}
        self._listener: socket.socket | None = None
        self._unix_path: str | None = None
        self.address = ""
        self._transport = transport or os.environ.get(
            "SHEEPRL_TPU_FLOCK_TRANSPORT", "unix"
        )
        # crash-resume: restore_sidecar pins the pre-crash address so
        # surviving actors' reconnect backoff finds the rehosted service
        self._requested_address: str | None = None
        self._restored = False
        # eviction: ActorFleet hooks this to apply its respawn budget to
        # actors whose heartbeat went stale (<= 0 disables the monitor)
        self.on_evict: Callable[[int], None] | None = None
        self.heartbeat_timeout_s = float(
            os.environ.get(HEARTBEAT_TIMEOUT_VAR, DEFAULT_HEARTBEAT_TIMEOUT_S)
        )
        # sheepscope: provenance of the chunk the last `next_chunk()` call
        # returned ({actor, span, weight_version, wait_s, queued_s} or None)
        # — the learner's drain span parents on it without the return type
        # of next_chunk changing
        self.last_drain: dict[str, Any] | None = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> str:
        requested = (
            wire.parse_address(self._requested_address)
            if self._requested_address
            else None
        )
        if requested is not None and requested[0] == "tcp":
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((requested[1], requested[2]))
            self.address = self._requested_address
        elif requested is not None:
            # rehost at the pre-crash unix path: the SIGKILLed process never
            # unlinked it, and a stale socket file refuses new connects
            path = requested[1]
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            try:
                os.unlink(path)
            except OSError:
                pass
            self._unix_path = path
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(path)
            self.address = self._requested_address
        elif self._transport == "tcp":
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(("127.0.0.1", 0))
            self.address = wire.format_address(
                "tcp", "127.0.0.1", srv.getsockname()[1]
            )
        else:
            # a short tempdir path: AF_UNIX paths cap at ~107 bytes
            sock_dir = tempfile.mkdtemp(prefix="flock-")
            self._unix_path = os.path.join(sock_dir, "svc.sock")
            srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            srv.bind(self._unix_path)
            self.address = wire.format_address("unix", self._unix_path)
        srv.listen(self.n_actors * 2 + 2)
        self._listener = srv
        t = threading.Thread(
            target=self._accept_loop, name="flock-accept", daemon=True
        )
        t.start()
        self._threads.append(t)
        if self.heartbeat_timeout_s > 0:
            mon = threading.Thread(
                target=self._monitor_loop, name="flock-monitor", daemon=True
            )
            mon.start()
            self._threads.append(mon)
        self._event("flock.started", address=self.address, mode=self.mode)
        if self._restored:
            self._event(
                "flock.resumed",
                address=self.address,
                rows_total=self._rows_total,
                weight_version=self._weight_version,
            )
        return self.address

    def close(self) -> None:
        self._stop.set()
        for sock in [self._listener, *self._conns]:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
        with self._lock:
            receivers = list(self._shm_rx.values())
            self._shm_rx.clear()
        for rx in receivers:
            rx.stop(unlink=True)
        for t in self._threads:
            t.join(timeout=2.0)
        if self._unix_path:
            try:
                os.unlink(self._unix_path)
                os.rmdir(os.path.dirname(self._unix_path))
            except OSError:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- socket side ----------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            t = threading.Thread(
                target=self._serve, args=(conn,), name="flock-conn", daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        actor_id = None
        role = "data"
        try:
            frame = wire.recv_frame(conn)
            if frame is None:
                return
            if frame[0] == wire.PROFILE:
                # sheepscope on-demand profiling: a bare PROFILE connection
                # (no HELLO) opens a bounded jax.profiler window in THIS
                # process and replies with the artifact path
                from ..telemetry.trace import handle_profile_frame

                log_dir = getattr(self._telem, "log_dir", None)
                wire.send_json(
                    conn,
                    wire.PROFILE,
                    handle_profile_frame(
                        json.loads(frame[1].decode() or "{}"), log_dir
                    ),
                )
                return
            if frame[0] == wire.RELAY_HELLO:
                # a relay's upstream multiplexed connection (ISSUE 19)
                role = "relay"
                self._serve_relay(conn, json.loads(frame[1].decode()))
                return
            if frame[0] != wire.HELLO:
                return
            hello = json.loads(frame[1].decode())
            actor_id = int(hello["actor_id"])
            role = hello.get("role", "data")
            # actor_id -1 is a relay's weight-cache poller: it serves many
            # actors, so it carries no single actor identity
            known = actor_id in self._actors or (
                role == "weights" and actor_id == -1
            )
            if not known or hello.get("proto") != PROTO_VERSION:
                wire.send_json(
                    conn, wire.ERROR, {"error": f"bad hello {hello!r}"}
                )
                return
            if role == "weights":
                self._serve_weights(conn)
                return
            self._register(actor_id, hello)
            with self._lock:
                self._data_conns[actor_id] = conn
            wire.send_json(
                conn,
                wire.WELCOME,
                {
                    "actor_id": actor_id,
                    "shard_capacity": self.capacity_rows,
                    "weight_version": self._weight_version,
                    "random_phase": self._random_phase,
                    "generation": self._actors[actor_id].generation,
                },
            )
            while not self._stop.is_set():
                frame = wire.recv_frame(conn)
                if frame is None:
                    break
                kind, payload = frame
                if kind == wire.PUSH:
                    self._handle_push(conn, actor_id, payload)
                elif kind == wire.HEARTBEAT:
                    self._handle_heartbeat(conn, actor_id, payload)
                elif kind == wire.SHM_ATTACH:
                    self._handle_shm_attach(
                        conn, actor_id, json.loads(payload.decode())
                    )
                elif kind == wire.BYE:
                    break
                else:
                    wire.send_json(
                        conn,
                        wire.ERROR,
                        {"error": f"unexpected {wire.KIND_NAMES.get(kind, kind)}"},
                    )
        except (wire.FrameError, OSError, ValueError, KeyError) as err:
            # the failure already killed this connection; the service keeps
            # serving every other actor, but the error must leave a receipt
            # (SL012: swallowed handlers hide exactly the chaos-CI signals)
            if not self._stop.is_set():
                self._event(
                    "flock.conn_error",
                    actor_id=actor_id,
                    role=role,
                    error=f"{type(err).__name__}: {err}",
                )
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if actor_id in self._actors and role == "data":
                with self._lock:
                    if self._data_conns.get(actor_id) is conn:
                        del self._data_conns[actor_id]
                # the ring rides the data connection's lifetime: a dead
                # actor's receiver drains what was committed, detaches and
                # unlinks (the creator may be SIGKILLed and unable to).
                # A rejoined actor has already swapped in a NEW receiver —
                # only stop the one this connection attached.
                with self._lock:
                    rx = self._shm_rx.get(actor_id)
                    if rx is not None and rx.conn is conn:
                        del self._shm_rx[actor_id]
                    else:
                        rx = None
                if rx is not None:
                    rx.stop(unlink=True)
                self._deregister(actor_id)

    def _serve_weights(self, conn: socket.socket) -> None:
        """Dedicated weight-pull connection: GET_WEIGHTS request/reply only,
        so a slow snapshot transfer never sits between two PUSHes."""
        while not self._stop.is_set():
            frame = wire.recv_frame(conn)
            if frame is None:
                return
            kind, payload = frame
            if kind != wire.GET_WEIGHTS:
                wire.send_json(conn, wire.ERROR, {"error": "weights conn"})
                return
            have = json.loads(payload.decode()).get("have_version", -1)
            with self._lock:
                version, blob = self._weight_version, self._weight_payload
            if blob is None or have == version:
                wire.send_json(conn, wire.WEIGHTS_UNCHANGED, {"version": version})
            else:
                wire.send_frame(conn, wire.WEIGHTS, blob)

    def _register(self, actor_id: int, hello: dict) -> None:
        with self._lock:
            st = self._actors[actor_id]
            rejoin = st.ever_connected
            st.generation += 1 if rejoin else 0
            st.connected = True
            st.ever_connected = True
            st.pid = int(hello.get("pid", -1))
            st.last_heartbeat = time.monotonic()
            # a (re)joining actor is a fresh process as far as its monotonic
            # clock is concerned: drop the old baselines
            st.sender_mono0 = None
            st.recv_mono0 = None
            st.last_sender_mono = None
            self._membership.notify_all()
        if rejoin:
            self._event(
                "flock.actor_rejoined",
                actor_id=actor_id,
                generation=st.generation,
                weight_version=self._weight_version,
            )
        else:
            self._event("flock.actor_joined", actor_id=actor_id, pid=st.pid)

    def _deregister(self, actor_id: int) -> None:
        with self._lock:
            st = self._actors[actor_id]
            was = st.connected
            st.connected = False
        if was:
            self._event(
                "flock.actor_disconnected",
                actor_id=actor_id,
                rows=st.rows,
                env_steps=st.env_steps,
            )

    def _monitor_loop(self) -> None:
        """Heartbeat staleness eviction: the `heartbeat_age_s` gauge was
        recorded but never acted on — a wedged actor (e.g. partitioned
        mid-push) held its connection slot forever. Past the timeout the
        connection is freed (the shard is KEPT for rejoin) and ActorFleet's
        `on_evict` hook applies the normal respawn budget. The age is the
        sender-monotonic one (`_ActorState.heartbeat_age`) whenever the
        actor stamps its frames — wall clocks never enter the decision."""
        poll = max(0.1, min(self.heartbeat_timeout_s / 4.0, 1.0))
        while not self._stop.wait(poll):
            now = time.monotonic()
            stale = []
            with self._lock:
                for aid, st in self._actors.items():
                    if not st.connected or not st.last_heartbeat:
                        continue
                    age = st.heartbeat_age(now)
                    if age > self.heartbeat_timeout_s:
                        stale.append((aid, age))
            for aid, age in stale:
                self.evict(aid, age=age)

    def evict(self, actor_id: int, age: float | None = None) -> None:
        """Free a stale actor's connection; keep its shard for rejoin."""
        with self._lock:
            conn = self._data_conns.pop(actor_id, None)
        self._event(
            "flock.actor_stale",
            actor_id=actor_id,
            age_s=None if age is None else round(age, 3),
            timeout_s=self.heartbeat_timeout_s,
        )
        if conn is not None:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self.on_evict is not None:
            self.on_evict(actor_id)

    def _handle_shm_attach(self, conn, actor_id: int, req: dict) -> None:
        """SHM_ATTACH: the colocated actor created a ring (flock/shm.py);
        attach it and start a drain thread feeding `_ingest_push` — from
        here on this actor's PUSH payloads arrive through shared memory
        and the socket carries only control frames. A re-attach (actor
        rejoined with a fresh ring) replaces the old receiver."""
        from .shm import ShmReceiver, ShmRing

        try:
            ring = ShmRing.attach(str(req["name"]))
        except (OSError, KeyError, ValueError) as err:
            wire.send_json(
                conn,
                wire.SHM_ATTACH,
                {"ok": False, "error": f"{type(err).__name__}: {err}"},
            )
            return

        def on_corrupt(_payload, aid=actor_id):
            with self._lock:
                self._tx["shm_corrupt"] += 1
            self._event("flock.shm_corrupt", actor_id=aid)

        rx = ShmReceiver(
            ring,
            on_payload=lambda p, aid=actor_id: self._ingest_push(
                aid, p, transport="shm"
            ),
            on_corrupt=on_corrupt,
            name=f"flock-shm-drain-{actor_id}",
        )
        rx.conn = conn  # ties the receiver to this connection's lifetime
        with self._lock:
            old = self._shm_rx.get(actor_id)
            self._shm_rx[actor_id] = rx
        if old is not None:
            old.stop(unlink=True)
        rx.start()
        self._event(
            "flock.shm_attached",
            actor_id=actor_id,
            ring=ring.name,
            slots=ring.slots,
            slot_bytes=ring.slot_bytes,
        )
        wire.send_json(conn, wire.SHM_ATTACH, {"ok": True})

    def _handle_push(self, conn, actor_id: int, payload: bytes) -> None:
        reply = self._ingest_push(actor_id, payload, transport="socket")
        wire.send_json(conn, wire.PUSH_OK, reply)

    def _ingest_push(
        self, actor_id: int, payload: bytes, transport: str = "socket"
    ) -> dict:
        """Apply one PUSH payload to the actor's shard, whatever transport
        carried it (socket handler, shm drain thread, relay batch), and
        return the PUSH_OK reply fields. Shard contents are byte-identical
        across transports — the payload IS the contract."""
        ops, meta = unpack_push(payload)
        rows = int(meta.get("rows") or 0)
        trace = meta.get("trace") or {}
        # ingest span: the learner-side receipt of this PUSH, parented on
        # the actor's push span so sheeptrace can stitch across processes
        ingest_span = None
        if self._telem is not None and trace.get("span"):
            ingest_span = self._telem.tracer.point(
                "ingest",
                parent=trace.get("span"),
                actor=actor_id,
                rows=rows,
                weight_version=meta.get("weight_version"),
            )
        if self.mode == "buffer":
            shard = self._shards[actor_id]
            with self._shard_locks[actor_id]:
                for tree, indices in ops:
                    shard.add(tree, indices=indices)
        else:
            prov = {
                "actor": actor_id,
                "span": ingest_span,
                "weight_version": meta.get("weight_version"),
                "t_queued": time.monotonic(),
            }
            with self._lock:
                q = self._chunks[actor_id]
                cap = self._chunk_cap.get(actor_id)
                if cap is None and rows:
                    cap = max(2, self.capacity_rows // rows)
                    self._chunk_cap[actor_id] = cap
                if cap and len(q) >= cap:
                    q.popleft()
                    self._chunks_dropped += 1
                for tree, _ in ops:
                    q.append((tree, prov))
                self._chunk_ready.notify_all()
        with self._lock:
            st = self._actors[actor_id]
            st.rows += rows
            st.env_steps = int(meta.get("env_steps") or st.env_steps)
            st.weight_version = int(
                meta.get("weight_version", st.weight_version)
            )
            st.last_heartbeat = time.monotonic()
            st.note_sender_mono(trace.get("mono_ts"))
            self._rows_total += rows
            self._tx[f"{transport}_frames"] += 1
            self._tx[f"{transport}_bytes"] += len(payload)
            reply = {
                "rows_total": self._rows_total,
                "random_phase": self._random_phase,
                "weight_version": self._weight_version,
            }
        return reply

    def _handle_heartbeat(self, conn, actor_id: int, payload: bytes) -> None:
        reply = self._ingest_heartbeat(actor_id, payload)
        wire.send_json(conn, wire.HEARTBEAT_OK, reply)

    def _ingest_heartbeat(self, actor_id: int, payload: bytes) -> dict:
        hb = json.loads(payload.decode())
        with self._lock:
            st = self._actors[actor_id]
            st.last_heartbeat = time.monotonic()
            st.env_steps = int(hb.get("env_steps", st.env_steps))
            st.weight_version = int(hb.get("weight_version", st.weight_version))
            st.sps = float(hb.get("sps", st.sps))
            st.note_sender_mono(hb.get("mono_ts"))
            reply = {
                "random_phase": self._random_phase,
                "weight_version": self._weight_version,
                # clock-offset piggyback (sheepscope): our wall clock at
                # reply time — the actor's ClockSync does the NTP math
                "server_wall_ts": time.time(),
            }
        return reply

    def _serve_relay(self, conn: socket.socket, hello: dict) -> None:
        """One relay's upstream connection (ISSUE 19): strict
        request/reply. PUSH_BATCH applies every batched PUSH payload and
        gets one aggregate PUSH_OK; RELAY_FWD-wrapped actor control frames
        (HELLO/HEARTBEAT/BYE) are processed exactly as if the actor were
        directly connected — registration, generation bumps and rejoin
        events included — and the normal reply rides back RELAY_FWD-
        wrapped. A dying relay connection deregisters every actor it
        forwarded, mirroring per-actor socket teardown."""
        relay_id = int(hello.get("relay_id", -1))
        if hello.get("proto") != PROTO_VERSION:
            wire.send_json(
                conn, wire.ERROR, {"error": f"bad relay hello {hello!r}"}
            )
            return
        members: set[int] = set()
        with self._lock:
            welcome = {
                "relay_id": relay_id,
                "shard_capacity": self.capacity_rows,
                "weight_version": self._weight_version,
                "random_phase": self._random_phase,
            }
        wire.send_json(conn, wire.WELCOME, welcome)
        self._event("flock.relay_joined", relay_id=relay_id,
                    pid=int(hello.get("pid", -1)))
        try:
            while not self._stop.is_set():
                frame = wire.recv_frame(conn)
                if frame is None:
                    break
                kind, payload = frame
                if kind == wire.PUSH_BATCH:
                    items = wire.unpack_push_batch(payload)
                    reply: dict = {}
                    for aid, push_payload in items:
                        if aid in self._actors:
                            reply = self._ingest_push(
                                aid, push_payload, transport="relay"
                            )
                    with self._lock:
                        self._tx["relay_batches"] += 1
                    if not reply:
                        with self._lock:
                            reply = {
                                "rows_total": self._rows_total,
                                "random_phase": self._random_phase,
                                "weight_version": self._weight_version,
                            }
                    wire.send_json(conn, wire.PUSH_OK, reply)
                elif kind == wire.RELAY_FWD:
                    aid, inner_kind, inner = wire.unpack_relay_fwd(payload)
                    if aid not in self._actors:
                        wire.send_json(
                            conn, wire.ERROR, {"error": f"unknown actor {aid}"}
                        )
                        continue
                    if inner_kind == wire.HELLO:
                        inner_hello = json.loads(inner.decode())
                        self._register(aid, inner_hello)
                        members.add(aid)
                        with self._lock:
                            wmsg = {
                                "actor_id": aid,
                                "shard_capacity": self.capacity_rows,
                                "weight_version": self._weight_version,
                                "random_phase": self._random_phase,
                                "generation": self._actors[aid].generation,
                            }
                        out = (wire.WELCOME, json.dumps(wmsg).encode())
                    elif inner_kind == wire.HEARTBEAT:
                        out = (
                            wire.HEARTBEAT_OK,
                            json.dumps(
                                self._ingest_heartbeat(aid, inner)
                            ).encode(),
                        )
                    elif inner_kind == wire.BYE:
                        members.discard(aid)
                        self._deregister(aid)
                        out = (wire.BYE, b"")
                    else:
                        out = (
                            wire.ERROR,
                            json.dumps(
                                {"error": f"unexpected fwd kind {inner_kind}"}
                            ).encode(),
                        )
                    wire.send_frame(
                        conn, wire.RELAY_FWD, wire.pack_relay_fwd(aid, *out)
                    )
                elif kind == wire.BYE:
                    break
                else:
                    wire.send_json(
                        conn,
                        wire.ERROR,
                        {"error": f"unexpected {wire.KIND_NAMES.get(kind, kind)}"},
                    )
        finally:
            self._event("flock.relay_disconnected", relay_id=relay_id,
                        actors=sorted(members))
            for aid in members:
                self._deregister(aid)

    # -- learner side ---------------------------------------------------------

    def publish(self, leaves, span: str | None = None) -> int:
        """Snapshot a new weight version from flattened model leaves. The
        device->host pull and the byte packing happen ONCE here; every
        actor pull then reuses the cached frame. `span` is the learner's
        publish span id — it rides the WEIGHTS meta so the actor's next
        collect span can parent on the version it acts with."""
        from ..data.wire import pack_leaves

        host_leaves = [np.asarray(leaf) for leaf in leaves]
        blob = pack_leaves(host_leaves)
        with self._lock:
            self._weight_version += 1
            version = self._weight_version
            wmeta: dict[str, Any] = {"version": version}
            if span:
                wmeta["span"] = span
            meta = json.dumps(wmeta).encode()
            self._weight_payload = _U32.pack(len(meta)) + meta + blob
            self._publish_ts[version] = time.monotonic()
            # keep the timestamp map bounded
            for old in [v for v in self._publish_ts if v < version - 64]:
                del self._publish_ts[old]
        return version

    @property
    def weight_version(self) -> int:
        return self._weight_version

    def set_random_phase(self, flag: bool) -> None:
        with self._lock:
            self._random_phase = bool(flag)

    def wait_for_actors(self, n: int | None = None, timeout: float = 60.0) -> bool:
        """Block until n actors (default: all) have registered."""
        want = self.n_actors if n is None else n
        deadline = time.monotonic() + timeout
        with self._membership:
            # SY005: every wait below re-checks its predicate in the while
            # head — a spurious or stale notify can never satisfy the wait
            while self.actors_alive() < want:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    return False
                self._membership.wait(timeout=min(left, 0.5))
        return True

    def actors_alive(self) -> int:
        return sum(1 for st in self._actors.values() if st.connected)

    def next_chunk(self, timeout: float | None = None):
        """Chunks mode: pop the next rollout chunk, round-robin across
        actors so one fast actor cannot starve the rest. None on timeout.
        Sets `self.last_drain` to the popped chunk's sheepscope provenance
        (actor, ingest span, weight version, this call's wait and the
        chunk's queue dwell) — the learner reads it right after the call."""
        t_enter = time.monotonic()
        deadline = None if timeout is None else t_enter + timeout
        with self._chunk_ready:
            while True:
                ids = sorted(self._chunks)
                for k in range(len(ids)):
                    aid = ids[(self._drain_order + k) % len(ids)]
                    if self._chunks[aid]:
                        self._drain_order = (ids.index(aid) + 1) % len(ids)
                        tree, prov = self._chunks[aid].popleft()
                        now = time.monotonic()
                        self.last_drain = {
                            "actor": prov.get("actor", aid),
                            "span": prov.get("span"),
                            "weight_version": prov.get("weight_version"),
                            "wait_s": round(now - t_enter, 6),
                            "queued_s": round(
                                now - prov.get("t_queued", now), 6
                            ),
                        }
                        return tree
                if self._stop.is_set():
                    self.last_drain = None
                    return None
                left = None if deadline is None else deadline - time.monotonic()
                if left is not None and left <= 0:
                    self.last_drain = None
                    return None
                self._chunk_ready.wait(timeout=0.5 if left is None else min(left, 0.5))

    def plan_partition(self, batch_size: int) -> list[tuple[int, int]]:
        """-> [(actor_id, n), ...] splitting `batch_size` across shards.
        The remainder rotates from `_sample_rr` instead of always landing
        on the first live shards (ISSUE 19 satellite): deterministic, and
        over many calls every shard draws the same count to within one.
        ADVANCES the rotation — callers draw exactly once per plan; the
        counter is part of the sample state, so the assembler's rewind and
        the crash-resume sidecar both restore it."""
        ready = sorted(self._shards)
        k = len(ready)
        counts = [batch_size // k] * k
        rem = batch_size % k
        for i in range(rem):
            counts[(self._sample_rr + i) % k] += 1
        if rem:
            self._sample_rr = (self._sample_rr + rem) % k
        return list(zip(ready, counts))

    @property
    def epoch(self) -> int:
        """Total write epoch across shards (buffer mode): bumps on every
        ingested op, whatever transport carried it. The assembler's
        consistency guard (flock/assemble.py, same contract as the PR-3
        SamplePrefetcher) compares snapshots of this."""
        return sum(
            int(getattr(shard, "epoch", 0)) for shard in self._shards.values()
        )

    def get_sample_state(self) -> dict:
        """Snapshot everything `sample()` consumes besides shard contents:
        the remainder rotation and each shard's sampler PRNG state."""
        state: dict[str, Any] = {"rr": self._sample_rr, "shards": {}}
        for aid, shard in self._shards.items():
            if hasattr(shard, "get_sample_state"):
                with self._shard_locks[aid]:
                    state["shards"][aid] = shard.get_sample_state()
        return state

    def set_sample_state(self, state: dict) -> None:
        self._sample_rr = int(state.get("rr", 0))
        for aid, shard_state in state.get("shards", {}).items():
            shard = self._shards.get(int(aid))
            if shard is not None and hasattr(shard, "set_sample_state"):
                with self._shard_locks[int(aid)]:
                    shard.set_sample_state(shard_state)

    def sample(self, batch_size: int, **kw):
        """Buffer mode: partition the batch across shards that can serve it
        and concatenate — local calls only, no socket. Shards still warming
        up (or disconnected mid-fill) are skipped; the batch re-partitions
        over the rest."""
        parts, served, missing = [], [], 0
        for aid, n in self.plan_partition(batch_size):
            if n == 0:
                continue
            with self._shard_locks[aid]:
                try:
                    parts.append(self._shards[aid].sample(n, **kw))
                    served.append(aid)
                except (ValueError, RuntimeError):
                    missing += n
        if not parts:
            # the partition may have skipped (n == 0) the only shard with
            # data — e.g. batch_size < n_actors early in the run. Any single
            # shard that can serve the WHOLE batch keeps training moving.
            for aid in sorted(self._shards):
                with self._shard_locks[aid]:
                    try:
                        return self._shards[aid].sample(batch_size, **kw)
                    except (ValueError, RuntimeError):
                        continue
            raise RuntimeError("no flock shard could serve the sample request")
        if missing:
            # a shard still warming up drops out; its slice tops up from a
            # shard that CAN serve, so the batch shape never shrinks (the
            # train jit's aval is part of the warm-compile contract)
            aid = served[0]
            with self._shard_locks[aid]:
                parts.append(self._shards[aid].sample(missing, **kw))
        axis = 2 if "sequence_length" in kw else 0
        return {
            k: np.concatenate([p[k] for p in parts], axis=axis)
            for k in parts[0]
        }

    def rows_total(self) -> int:
        return self._rows_total

    def shard(self, actor_id: int):
        return self._shards.get(actor_id)

    def connected_ids(self) -> set[int]:
        with self._lock:
            return {
                aid for aid, st in self._actors.items() if st.connected
            }

    def actor_pid(self, actor_id: int) -> int:
        with self._lock:
            return self._actors[actor_id].pid

    # -- crash-resume sidecar -------------------------------------------------

    def sidecar_path(self, ckpt_path: str) -> str:
        return str(ckpt_path) + SIDECAR_SUFFIX

    def save_sidecar(self, ckpt_path: str) -> str:
        """Snapshot the service next to a learner checkpoint: per-actor
        shard contents (the buffers' own `to_bytes` wire codecs keep this
        bit-exact, sampler PRNG included), the membership table, and the
        bound address. Written atomically (tmp + rename) so a crash mid-save
        leaves the previous sidecar intact."""
        from ..data.wire import pack_tree

        blobs: list[bytes] = []
        actors: dict[str, dict[str, Any]] = {}
        with self._lock:
            for aid in range(self.n_actors):
                st = self._actors[aid]
                actors[str(aid)] = {
                    "generation": st.generation,
                    "ever_connected": st.ever_connected,
                    "env_steps": st.env_steps,
                    "weight_version": st.weight_version,
                    "rows": st.rows,
                }
                if self.mode == "buffer":
                    with self._shard_locks[aid]:
                        blobs.append(self._shards[aid].to_bytes())
                else:
                    chunks = list(self._chunks[aid])
                    parts = [_U32.pack(len(chunks))]
                    # provenance is NOT persisted: its span ids refer to the
                    # crashed run's shards, and its t_queued to a dead
                    # monotonic clock — restored chunks restart clean
                    for tree, _prov in chunks:
                        blob = pack_tree(tree)
                        parts += [_U64.pack(len(blob)), blob]
                    blobs.append(b"".join(parts))
            meta = {
                "algo": self.algo,
                "mode": self.mode,
                "n_actors": self.n_actors,
                "capacity_rows": self.capacity_rows,
                "address": self.address,
                "weight_version": self._weight_version,
                "rows_total": self._rows_total,
                "chunks_dropped": self._chunks_dropped,
                "random_phase": self._random_phase,
                "sample_rr": self._sample_rr,
                "chunk_cap": {str(k): v for k, v in self._chunk_cap.items()},
                "actors": actors,
                "blob_lens": [len(b) for b in blobs],
            }
        mb = json.dumps(meta).encode()
        path = self.sidecar_path(ckpt_path)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(SIDECAR_MAGIC + _U32.pack(len(mb)) + mb)
            for blob in blobs:
                fh.write(blob)
        os.replace(tmp, path)
        return path

    def restore_sidecar(self, ckpt_path: str) -> bool:
        """Load a sidecar written by `save_sidecar`; call BEFORE `start()`
        so the service rehosts at the pre-crash address. Returns False when
        no sidecar rides this checkpoint."""
        path = self.sidecar_path(ckpt_path)
        if not os.path.exists(path):
            return False
        with open(path, "rb") as fh:
            data = fh.read()
        if data[:4] != SIDECAR_MAGIC:
            raise ValueError(f"bad flock sidecar magic in {path!r}")
        (meta_len,) = _U32.unpack_from(data, 4)
        meta = json.loads(data[8 : 8 + meta_len].decode())
        if (meta["algo"], meta["mode"], meta["n_actors"]) != (
            self.algo,
            self.mode,
            self.n_actors,
        ):
            raise ValueError(
                f"flock sidecar {path!r} was written for "
                f"algo={meta['algo']} mode={meta['mode']} "
                f"n_actors={meta['n_actors']}; this service is "
                f"algo={self.algo} mode={self.mode} n_actors={self.n_actors}"
            )
        off = 8 + meta_len
        with self._lock:
            self._requested_address = meta["address"]
            self._weight_version = int(meta["weight_version"])
            self._rows_total = int(meta["rows_total"])
            self._chunks_dropped = int(meta["chunks_dropped"])
            self._random_phase = bool(meta["random_phase"])
            self._sample_rr = int(meta.get("sample_rr", 0))
            self._chunk_cap = {
                int(k): int(v) for k, v in meta.get("chunk_cap", {}).items()
            }
            for aid in range(self.n_actors):
                st = self._actors[aid]
                saved = meta["actors"][str(aid)]
                st.generation = int(saved["generation"])
                st.ever_connected = bool(saved["ever_connected"])
                st.env_steps = int(saved["env_steps"])
                st.weight_version = int(saved["weight_version"])
                st.rows = int(saved["rows"])
                st.connected = False  # actors re-HELLO after the restart
            for aid, blob_len in enumerate(meta["blob_lens"]):
                blob = data[off : off + blob_len]
                off += blob_len
                if self.mode == "buffer":
                    self._shards[aid] = type(self._shards[aid]).from_bytes(
                        blob, storage="host"
                    )
                else:
                    from ..data.wire import unpack_tree

                    (n_chunks,) = _U32.unpack_from(blob, 0)
                    pos = 4
                    q = deque()
                    for _ in range(n_chunks):
                        (blen,) = _U64.unpack_from(blob, pos)
                        pos += 8
                        q.append((unpack_tree(blob[pos : pos + blen]), {}))
                        pos += blen
                    self._chunks[aid] = q
            self._restored = True
        return True

    # -- observability --------------------------------------------------------

    def gauges(self) -> dict[str, float]:
        now = time.monotonic()
        with self._lock:
            out: dict[str, float] = {
                "Flock/actors_alive": float(self.actors_alive()),
                "Flock/weight_version": float(self._weight_version),
                "Flock/rows_total": float(self._rows_total),
                "Flock/chunks_dropped": float(self._chunks_dropped),
            }
            for key, val in self._tx.items():
                out[f"Flock/transport/{key}"] = float(val)
            out["Flock/transport/shm_rings"] = float(len(self._shm_rx))
            for aid, st in self._actors.items():
                if not st.ever_connected:
                    continue
                prefix = f"Flock/actor{aid}"
                lag = max(0, self._weight_version - max(st.weight_version, 0))
                # staleness: how long ago the version this actor acts with
                # stopped being current (0 while it holds the latest)
                if lag == 0:
                    staleness = 0.0
                else:
                    superseded = self._publish_ts.get(
                        max(st.weight_version, 0) + 1
                    )
                    staleness = 0.0 if superseded is None else now - superseded
                if self.mode == "buffer":
                    fill = min(st.rows, self.capacity_rows) / max(
                        self.capacity_rows, 1
                    )
                else:
                    cap = self._chunk_cap.get(aid, 0)
                    fill = len(self._chunks[aid]) / cap if cap else 0.0
                out[f"{prefix}/env_steps_s"] = float(st.sps)
                out[f"{prefix}/env_steps"] = float(st.env_steps)
                out[f"{prefix}/weight_version"] = float(st.weight_version)
                out[f"{prefix}/version_lag"] = float(lag)
                out[f"{prefix}/staleness_s"] = float(staleness)
                out[f"{prefix}/shard_fill"] = float(fill)
                out[f"{prefix}/heartbeat_age_s"] = (
                    float(st.heartbeat_age(now)) if st.last_heartbeat else -1.0
                )
                out[f"{prefix}/connected"] = float(st.connected)
                out[f"{prefix}/generation"] = float(st.generation)
        return out

    def _event(self, name: str, **data) -> None:
        if self._telem is not None:
            self._telem.event(name, **data)
        else:
            telemetry.emit(name, **data)
