"""Zero-copy shared-memory ring transport for colocated flock actors
(ISSUE 19, tentpole b).

An actor that shares the learner's host does not need a socket for its
bulk rollout traffic: it creates a `multiprocessing.shared_memory` ring,
announces it over the ordinary FLK1 data connection (SHM_ATTACH), and
from then on every PUSH payload — the exact `service.pack_push` bytes
the socket would carry, `data/wire.py` width-class packing and all — is
committed into a ring slot the service's drain thread ingests in place.
The socket stays open for control frames only (heartbeats, BYE), and
any failure on the ring path falls back to it transparently.

Ring layout (one writer = the actor, one reader = the service):

    header(48) = magic(4)=b"SFR1" | version(u32) | slots(u32) | pad(u32)
                 | slot_bytes(u64) | produced(u64) | consumed(u64) | pad(u64)
    slot[i](slot_bytes) = seq(u64) | length(u64) | crc32(u32) | pad(u32)
                          | payload[length]

Slot commits use a seqlock-style header: for absolute frame position
`p`, slot `p % slots` is committed at `seq == 2*(p // slots) + 2`; the
writer stores `seq-1` (odd: write in progress), the payload, then the
even seq — a reader that observes the even target seq AND
`produced > p` sees fully-committed bytes, and a torn write can never
masquerade as a commit. `produced`/`consumed` are single-writer
cursors: the producer advances `produced` after the slot commit, the
consumer advances `consumed` after copying the payload out, and the
producer blocks (bounded) while the ring is full. Payloads carry a
CRC32; a mismatch (injected `net.corrupt`, or a writer that died
mid-slot and was force-committed) skips the slot with a receipt instead
of poisoning the shard.

Fault injection: the producer runs every payload through
`wire.inject_shm_send`, so the sheepfault `net.*` clauses fire on shm
frames exactly like socket frames — `net.partition` detaches the ring
and (via the opened partition window) forces the socket fallback to
wait the window out.

Sizing knobs (howto/distributed_actors.md):

    SHEEPRL_TPU_FLOCK_SHM_SLOTS       ring depth in frames (default 8)
    SHEEPRL_TPU_FLOCK_SHM_SLOT_BYTES  payload capacity per slot (default
                                      sized off the first pushed frame;
                                      oversize frames fall back to the
                                      socket for that push)
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

from multiprocessing import shared_memory

__all__ = ["ShmRing", "ShmReceiver", "ring_geometry", "shm_enabled_for"]

MAGIC = b"SFR1"
VERSION = 1

_HEADER = struct.Struct("<4sIIIQQQQ")  # magic, ver, slots, pad, slot_bytes, produced, consumed, pad
_SLOT = struct.Struct("<QQII")  # seq, length, crc32, pad

HEADER_BYTES = _HEADER.size
SLOT_HEADER_BYTES = _SLOT.size

DEFAULT_SLOTS = 8
SLOTS_VAR = "SHEEPRL_TPU_FLOCK_SHM_SLOTS"
SLOT_BYTES_VAR = "SHEEPRL_TPU_FLOCK_SHM_SLOT_BYTES"
ENABLE_VAR = "SHEEPRL_TPU_FLOCK_SHM"

_PRODUCED_OFF = 24
_CONSUMED_OFF = 32


def shm_enabled_for(actor_id: int) -> bool:
    """Transport policy for one actor, from SHEEPRL_TPU_FLOCK_SHM:
    unset/'0'/'off' -> socket (the pre-ISSUE-19 behavior, bit-exact);
    '1'/'all'/'on' -> every actor attaches a ring; a comma list of ids
    ('0,2,4') -> exactly those actors, the rest stay on the socket —
    the mixed topology the CI flock smoke exercises."""
    raw = (os.environ.get(ENABLE_VAR) or "").strip().lower()
    if raw in ("", "0", "off", "no"):
        return False
    if raw in ("1", "all", "on", "yes"):
        return True
    try:
        ids = {int(tok) for tok in raw.split(",") if tok.strip()}
    except ValueError:
        return False
    return actor_id in ids


def ring_geometry(first_payload_len: int) -> tuple[int, int]:
    """-> (slots, slot_bytes) for a new ring, sized so the first pushed
    frame fits with headroom (frames are rollout-chunk sized and stable
    within a run; 2x covers episode-boundary reset ops riding along)."""
    slots = max(2, int(os.environ.get(SLOTS_VAR, DEFAULT_SLOTS)))
    override = os.environ.get(SLOT_BYTES_VAR)
    if override:
        payload_cap = max(1024, int(override))
    else:
        payload_cap = max(64 * 1024, 2 * first_payload_len)
    return slots, SLOT_HEADER_BYTES + payload_cap


def _untrack(shm) -> None:
    """Detach `shm` from this process's resource tracker: the ring's
    lifetime is owned explicitly (creator unlinks on close, the service
    unlinks on behalf of a SIGKILLed creator) — the tracker double-
    unlinking at interpreter exit only produces noise."""
    try:  # pragma: no cover - tracker internals vary across 3.x
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    # sheeplint: disable=SL012 — best-effort unregister against a private
    # stdlib API whose shape varies across 3.x; failure just means the
    # tracker keeps its (harmless, noisy-at-exit) double-unlink entry
    except Exception:
        pass


class ShmRing:
    """SPSC seqlock ring over one `multiprocessing.shared_memory` block."""

    def __init__(self, shm, *, created: bool):
        self._shm = shm
        self._created = created
        buf = shm.buf
        magic, ver, slots, _, slot_bytes, _, _, _ = _HEADER.unpack_from(buf, 0)
        if magic != MAGIC or ver != VERSION:
            raise ValueError(
                f"bad shm ring header in {shm.name!r}: "
                f"magic={magic!r} version={ver}"
            )
        self.slots = slots
        self.slot_bytes = slot_bytes
        self.payload_cap = slot_bytes - SLOT_HEADER_BYTES

    # -- construction ---------------------------------------------------------

    @classmethod
    def create(cls, *, slots: int, slot_bytes: int) -> "ShmRing":
        size = HEADER_BYTES + slots * slot_bytes
        shm = shared_memory.SharedMemory(create=True, size=size)
        _HEADER.pack_into(shm.buf, 0, MAGIC, VERSION, slots, 0, slot_bytes, 0, 0, 0)
        # zero seq on every slot so position 0's target (2) is unambiguous
        for i in range(slots):
            _SLOT.pack_into(shm.buf, HEADER_BYTES + i * slot_bytes, 0, 0, 0, 0)
        return cls(shm, created=True)

    @classmethod
    def attach(cls, name: str) -> "ShmRing":
        shm = shared_memory.SharedMemory(name=name, create=False)
        _untrack(shm)
        return cls(shm, created=False)

    @property
    def name(self) -> str:
        return self._shm.name

    # -- cursors --------------------------------------------------------------

    def _read_u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._shm.buf, off)[0]

    def _write_u64(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._shm.buf, off, value)

    @property
    def produced(self) -> int:
        return self._read_u64(_PRODUCED_OFF)

    @property
    def consumed(self) -> int:
        return self._read_u64(_CONSUMED_OFF)

    def backlog(self) -> int:
        return max(0, self.produced - self.consumed)

    # -- producer (actor side) ------------------------------------------------

    def try_push(self, data: bytes, crc: int | None = None) -> bool:
        """Commit one payload; False when the ring is full or the payload
        exceeds the slot capacity (the caller falls back to the socket)."""
        if len(data) > self.payload_cap:
            return False
        p = self.produced
        if p - self.consumed >= self.slots:
            return False
        if crc is None:
            crc = zlib.crc32(data)
        off = HEADER_BYTES + (p % self.slots) * self.slot_bytes
        target = 2 * (p // self.slots) + 2
        buf = self._shm.buf
        _SLOT.pack_into(buf, off, target - 1, len(data), crc & 0xFFFFFFFF, 0)
        buf[off + SLOT_HEADER_BYTES : off + SLOT_HEADER_BYTES + len(data)] = data
        _SLOT.pack_into(buf, off, target, len(data), crc & 0xFFFFFFFF, 0)
        self._write_u64(_PRODUCED_OFF, p + 1)
        return True

    def push(self, data: bytes, crc: int | None = None, timeout: float = 5.0) -> bool:
        """`try_push` with a bounded wait for ring space. False only on
        timeout (reader wedged or gone) or an oversize payload."""
        if len(data) > self.payload_cap:
            return False
        deadline = time.monotonic() + timeout
        while True:
            if self.try_push(data, crc):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.001)

    # -- consumer (service side) ----------------------------------------------

    def try_pop(self) -> tuple[bytes, bool] | None:
        """-> (payload, crc_ok) for the next committed frame, or None when
        the ring is empty. Advances `consumed` either way a frame is
        returned — a corrupt frame is consumed (and reported by the
        caller), never re-read forever."""
        c = self.consumed
        if self.produced <= c:
            return None
        off = HEADER_BYTES + (c % self.slots) * self.slot_bytes
        target = 2 * (c // self.slots) + 2
        buf = self._shm.buf
        seq, length, crc, _ = _SLOT.unpack_from(buf, off)
        if seq != target:
            # producer advanced `produced` but the slot commit is not
            # visible yet (or was torn): treat as empty, the next poll sees it
            return None
        length = min(length, self.payload_cap)
        data = bytes(buf[off + SLOT_HEADER_BYTES : off + SLOT_HEADER_BYTES + length])
        seq2 = _SLOT.unpack_from(buf, off)[0]
        if seq2 != target:
            return None
        self._write_u64(_CONSUMED_OFF, c + 1)
        return data, (zlib.crc32(data) & 0xFFFFFFFF) == crc

    def pop(self, timeout: float = 0.2) -> tuple[bytes, bool] | None:
        deadline = time.monotonic() + timeout
        while True:
            item = self.try_pop()
            if item is not None:
                return item
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.001)

    # -- teardown -------------------------------------------------------------

    def close(self, unlink: bool | None = None) -> None:
        """Detach; unlink defaults to creator-side (the attaching service
        passes unlink=True when it is tearing down a dead actor's ring)."""
        do_unlink = self._created if unlink is None else unlink
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass
        if do_unlink:
            try:
                self._shm.unlink()
            except (OSError, FileNotFoundError):
                pass


class ShmReceiver(threading.Thread):
    """Service-side drain thread for one attached ring: pops committed
    frames and hands the payload bytes to `on_payload` (the service's
    `_ingest_push`, or a relay's upstream batch queue). CRC mismatches go
    to `on_corrupt` instead and the slot is skipped. `stop()` drains
    whatever is already committed before detaching, so an actor's last
    pushes before a clean BYE are never lost."""

    def __init__(
        self,
        ring: ShmRing,
        *,
        on_payload,
        on_corrupt=None,
        name: str = "flock-shm-drain",
    ):
        super().__init__(name=name, daemon=True)
        self.ring = ring
        self._on_payload = on_payload
        self._on_corrupt = on_corrupt
        self._stop_evt = threading.Event()
        self.frames = 0
        self.bytes = 0
        self.corrupt = 0

    def run(self) -> None:
        while not self._stop_evt.is_set():
            self._drain_once(timeout=0.1)
        # final drain: consume everything committed before the stop
        while self._drain_once(timeout=0.0):
            pass

    def _drain_once(self, timeout: float) -> bool:
        item = self.ring.pop(timeout=timeout) if timeout else self.ring.try_pop()
        if item is None:
            return False
        payload, crc_ok = item
        if not crc_ok:
            self.corrupt += 1
            if self._on_corrupt is not None:
                self._on_corrupt(payload)
            return True
        self.frames += 1
        self.bytes += len(payload)
        self._on_payload(payload)
        return True

    def stop(self, join_timeout: float = 5.0, unlink: bool = True) -> None:
        self._stop_evt.set()
        if self.is_alive():
            self.join(timeout=join_timeout)
        self.ring.close(unlink=unlink)
