"""In-network sample pre-assembly across replay-service shards (ISSUE
19, tentpole a).

`ReplayService.sample` partitions a batch across per-actor shards,
draws each slice under that shard's lock, and concatenates — all
synchronously on the learner thread, right where the train step is
waiting. `BatchAssembler` moves that work off the critical path: the
moment a batch is SERVED, the next one is dispatched to per-shard
worker threads (`flock-assemble-{aid}`) that draw their slices
concurrently with the train step; the last finisher concatenates and
parks the assembled batch in a depth-1 ready slot the next `sample()`
call collects.

This is the PR-3 `SamplePrefetcher` contract generalized from one
buffer to a sharded service, and it keeps the SAME bit-exactness
guarantee: a pre-assembled batch is served only if the service's total
write `epoch` has not advanced past `max_staleness` since dispatch and
the call signature matches; otherwise the batch is discarded and the
FULL sample state — every shard's sampler PRNG plus the remainder-
rotation counter `plan_partition` consumed — is rewound to the
snapshot the dispatch took, so the fresh synchronous resample draws
exactly what the unassembled path would have. Assembler on vs off
trains on identical batches (tests/test_flock/test_assemble.py A/Bs
this), exactly like `--pipeline on|off`.

Dispatch pauses while writes land every serve-to-serve gap (strict
staleness can never hit there) and re-arms in quiet gaps — the same
`predict_quiet` heuristic as the prefetcher, sharing its
`PipelineStats` counters so `Pipeline/sample_hit_rate` reports this
path too.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ..parallel.pipeline import PipelineStats

__all__ = ["BatchAssembler"]

_STOP = object()


class _Assembly:
    """One in-flight assembled batch: per-shard slices land from worker
    threads; the last finisher concatenates (and tops up skipped shards)
    so `wait()` returns a ready batch — or None when nothing could serve
    (the caller's guard then rewinds and resamples synchronously)."""

    def __init__(self, sig, epoch0: int, state0: dict, kw: dict):
        self.sig = sig
        self.epoch0 = epoch0
        self.state0 = state0
        self.kw = kw
        self.batch: dict[str, np.ndarray] | None = None
        self._parts: list[tuple[int, Any]] = []  # (actor_id, slice)
        self._missing = 0
        self._pending = 0
        self._lock = threading.Lock()
        self._done = threading.Event()

    def expect(self, n_parts: int) -> None:
        self._pending = n_parts
        if n_parts == 0:
            self._done.set()

    def deliver(self, service, aid: int, part, missing: int) -> None:
        with self._lock:
            if part is not None:
                self._parts.append((aid, part))
            self._missing += missing
            self._pending -= 1
            last = self._pending == 0
        if last:
            self._finish(service)
            self._done.set()

    def _finish(self, service) -> None:
        if not self._parts:
            return  # nothing served: the guard falls back synchronously
        if self._missing:
            # same top-up rule as ReplayService.sample: a warming-up shard's
            # slice comes from one that CAN serve, keeping the batch shape
            # (the train jit's aval) intact
            aid = min(aid for aid, _ in self._parts)
            try:
                with service._shard_locks[aid]:
                    self._parts.append(
                        (aid, service._shards[aid].sample(self._missing, **self.kw))
                    )
            except (ValueError, RuntimeError):
                return
        self._parts.sort(key=lambda item: item[0])
        axis = 2 if "sequence_length" in self.kw else 0
        parts = [p for _, p in self._parts]
        self.batch = {
            k: np.concatenate([p[k] for p in parts], axis=axis)
            for k in parts[0]
        }

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)


class BatchAssembler:
    """Pre-assembling wrapper around a buffer-mode `ReplayService`; the
    learner calls `sample()` exactly as it would on the service."""

    def __init__(
        self,
        service,
        enabled: bool = True,
        max_staleness: int = 0,
        stats: PipelineStats | None = None,
    ):
        self.service = service
        self.enabled = enabled and service.mode == "buffer"
        self.max_staleness = max_staleness
        self._stats = stats if stats is not None else PipelineStats()
        self._inflight: _Assembly | None = None
        self._last_epoch: int | None = None
        self._workers: dict[int, tuple[Any, threading.Thread]] = {}
        if self.enabled:
            import queue

            for aid in sorted(service._shards):
                q: "queue.Queue" = queue.Queue()
                t = threading.Thread(
                    target=self._worker,
                    args=(aid, q),
                    name=f"flock-assemble-{aid}",
                    daemon=True,
                )
                t.start()
                self._workers[aid] = (q, t)

    def __getattr__(self, name):  # delegate everything else to the service
        return getattr(self.service, name)

    # -- workers --------------------------------------------------------------

    def _worker(self, aid: int, q) -> None:
        service = self.service
        while True:
            task = q.get()
            if task is _STOP:
                return
            assembly, n = task
            part, missing = None, 0
            try:
                with service._shard_locks[aid]:
                    part = service._shards[aid].sample(n, **assembly.kw)
            except (ValueError, RuntimeError):
                missing = n
            assembly.deliver(service, aid, part, missing)

    def _dispatch(self, batch_size: int, sig, kw: dict) -> None:
        service = self.service
        state0 = service.get_sample_state()
        epoch0 = service.epoch
        assembly = _Assembly(sig, epoch0, state0, kw)
        parts = [
            (aid, n)
            for aid, n in service.plan_partition(batch_size)
            if n > 0 and aid in self._workers
        ]
        assembly.expect(len(parts))
        self._inflight = assembly
        self._stats.sample_prefetches += 1
        for aid, n in parts:
            self._workers[aid][0].put((assembly, n))

    # -- learner-facing -------------------------------------------------------

    def sample(self, batch_size: int, **kw):
        service = self.service
        if not self.enabled:
            return service.sample(batch_size, **kw)
        sig = (batch_size, tuple(sorted(kw.items())))
        batch = None
        if self._inflight is not None:
            assembly = self._inflight
            self._inflight = None
            # the wait ALSO quiesces the workers: no shard PRNG can mutate
            # underneath the rewind/resample below
            assembly.wait()
            epoch = service.epoch
            fresh = (
                assembly.sig == sig
                and assembly.batch is not None
                and epoch - assembly.epoch0 <= self.max_staleness
            )
            if fresh:
                self._stats.sample_hits += 1
                batch = assembly.batch
            else:
                # consistency guard: writes landed since dispatch (or the
                # signature changed, or nothing could serve) — discard and
                # rewind every shard's PRNG plus the remainder rotation to
                # the dispatch snapshot, so the fresh resample draws exactly
                # what the unassembled path would have (bit-exact on/off)
                self._stats.sample_misses += 1
                service.set_sample_state(assembly.state0)
        if batch is None:
            batch = service.sample(batch_size, **kw)
        epoch_now = service.epoch
        predict_quiet = (
            self.max_staleness > 0
            or self._last_epoch is None
            or epoch_now == self._last_epoch
        )
        self._last_epoch = epoch_now
        if predict_quiet:
            self._dispatch(batch_size, sig, kw)
        return batch

    def close(self) -> None:
        inflight, self._inflight = self._inflight, None
        if inflight is not None:
            inflight.wait(timeout=5.0)
        for q, _ in self._workers.values():
            q.put(_STOP)
        for _, t in self._workers.values():
            t.join(timeout=5.0)
        self._workers.clear()
        self.enabled = False
