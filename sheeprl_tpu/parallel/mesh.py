"""Device mesh + sharding utilities — the framework's distributed runtime.

Replaces the reference's Lightning-Fabric/torch.distributed layer (DDP wrap,
process groups, NCCL/Gloo collectives — SURVEY.md §2.7) with the JAX SPMD
model: one process per host drives all its local devices; parallelism is a
`jax.sharding.Mesh` with named axes; gradient all-reduce, data sharding and
cross-device statistics are XLA collectives inserted by the compiler from
sharding annotations, riding ICI within a slice and DCN across slices.

Axes:
  - "data": batch/env-parallelism (the reference's DDP world) — params
    replicated, batch sharded, grad psum implicit in the sharded jit.
  - "seq": optional sequence/context parallelism — the TIME axis of
    `[T, B]` sequence batches sharded across devices for the per-timestep
    stages (conv encoder/decoder, reward/continue heads), with sharding
    constraints resharding to batch-only around the sequential RSSM scan.
    GSPMD inserts the all-gather/all-to-all collectives over ICI. Lets the
    world-model losses scale to long sequences / small batches where pure
    data parallelism runs out of batch to shard.
  - decoupled player/trainer topologies use *sub-meshes* of the same device
    set (see sheeprl_tpu/parallel/decoupled.py) instead of torch process
    groups.

Multi-host: call `distributed_setup()` (jax.distributed.initialize) once per
host before building the mesh; `jax.devices()` then spans the pod and the
same annotations scale out with zero code change.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "distributed_setup",
    "make_mesh",
    "data_sharding",
    "replicated_sharding",
    "shard_batch",
    "replicate",
    "local_mesh_devices",
    "process_index",
    "assert_divisible",
    "constrain_scan_inputs",
    "constrain_time_batch",
    "make_constrain",
    "scan_batch_spec",
    "seq_axis_size",
    "shard_time_batch",
    "time_batch_sharding",
]


def assert_divisible(total: int, n_dev: int, what: str) -> None:
    """Refuse silently-degraded sharding: a batch dimension that does not
    divide the mesh would either need padding or fall back to replicated
    compute, so a bad size/device combination is a configuration error."""
    if n_dev > 1 and total % n_dev != 0:
        raise ValueError(
            f"{what}={total} is not divisible by the {n_dev}-device mesh; "
            f"pick a size that is a multiple of the device count"
        )


def distributed_setup(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize multi-host JAX (one call per host process). No-ops when
    single-host or when the TPU pod runtime auto-configures itself.

    Also the framework's hook for the persistent compilation cache: when
    SHEEPRL_TPU_COMPILE_CACHE names a directory, compiled executables are
    cached across processes/sessions. This is how the CPU receipt runners
    amortize the XLA:CPU conv-gradient compile pathology (the SAC-AE
    reconstruction jit alone costs ~16 min at pixel sizes — once), and it
    makes resumed TPU bench sessions rebuild closures nearly for free.
    Arming goes through the repo's ONE helper (`compile/cache.py`) — this
    call previously re-armed with a private 10 s compile-time floor, so
    after distributed setup every 0.5-10 s executable silently stopped
    being cached (ISSUE 5 satellite)."""
    cache_dir = os.environ.get("SHEEPRL_TPU_COMPILE_CACHE")
    if cache_dir:
        from ..compile.cache import arm_compile_cache

        arm_compile_cache(cache_dir)
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def process_index() -> int:
    return jax.process_index()


def local_mesh_devices(num_devices: int = -1, platform: Optional[str] = None):
    devices = jax.devices(platform) if platform else jax.devices()
    if num_devices > 0:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return devices


def make_mesh(
    num_devices: int = -1,
    platform: Optional[str] = None,
    axis_name: str = "data",
    devices: Any = None,
    seq_devices: int = 1,
) -> Mesh:
    """Data mesh over (a prefix of) the visible devices. With
    `seq_devices > 1` the mesh is 2-D `(axis_name, "seq")` of shape
    `(n // seq_devices, seq_devices)` — the context-parallel layout where
    "seq" shards the time axis of sequence batches."""
    if devices is None:
        devices = local_mesh_devices(num_devices, platform)
    devices = np.asarray(devices)
    if seq_devices > 1:
        if devices.size % seq_devices != 0:
            raise ValueError(
                f"seq_devices={seq_devices} must divide the device count "
                f"({devices.size})"
            )
        return Mesh(
            devices.reshape(devices.size // seq_devices, seq_devices),
            (axis_name, "seq"),
        )
    return Mesh(devices, (axis_name,))


def seq_axis_size(mesh: Mesh) -> int:
    """Size of the sequence/context-parallel axis (1 when absent)."""
    return mesh.shape.get("seq", 1)


def make_constrain(mesh: Optional[Mesh]):
    """Return `constrain(x, *spec)` applying a `with_sharding_constraint`
    when `mesh` has an active "seq" axis, else the identity — the helper the
    context-parallel train steps use at their phase boundaries."""
    if mesh is not None and seq_axis_size(mesh) > 1:

        def constrain(x, *spec):
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P(*spec))
            )

    else:

        def constrain(x, *spec):
            return x

    return constrain


_FULL_SCAN_SPEC = (None, ("data", "seq"))


def constrain_time_batch(constrain, *arrays, from_spec=None):
    """Apply the time-sharded `("seq", "data")` boundary spec to each of the
    `[T, B, ...]` RSSM scan outputs (the shared reshard point of every
    Dreamer-family train step).

    When the outputs come from the fully-sharded scan layout
    (`from_spec == (None, ("data", "seq"))`), reshard via the batch-on-"data"
    intermediate — see `constrain_scan_inputs` for why."""
    if from_spec == _FULL_SCAN_SPEC:
        arrays = tuple(constrain(a, None, "data") for a in arrays)
    return tuple(constrain(a, "seq", "data") for a in arrays)


def constrain_scan_inputs(constrain, scan_spec, *arrays):
    """Reshard time-sharded `[T, B, ...]` arrays into the RSSM scan layout.

    The direct reshard `("seq", "data") <-> (None, ("data", "seq"))` moves a
    mesh sub-axis between tensor axes in one step; GSPMD handles the forward
    but meets its TRANSPOSE in the backward pass with an involuntary full
    rematerialization (replicate-then-repartition — observed in the dp x sp
    DV3 backward, MULTICHIP_r02). Stepping through the batch-on-"data"
    intermediate splits both directions into a single-axis all-gather plus a
    local slice, which GSPMD partitions efficiently both ways."""
    if scan_spec == _FULL_SCAN_SPEC:
        arrays = tuple(constrain(a, None, "data") for a in arrays)
    out = tuple(constrain(a, *scan_spec) for a in arrays)
    return out if len(out) > 1 else out[0]


def scan_batch_spec(mesh: Optional[Mesh], batch_size: int) -> tuple:
    """Partition spec for the `[T, B, ...]` inputs of the sequential RSSM
    scan under context parallelism: batch over "data", replicated over
    "seq". The scan needs full T per shard, so its batch is the only
    shardable axis; the seq groups compute replicated scans (seq-times the
    scan FLOPs — a small, latency-bound slice of the step), and both phase
    boundaries are then single-axis reshards: a "seq" all-gather into the
    scan, a local time-slice out of it, in both differentiation directions.

    The alternative — sharding the scan batch over the WHOLE grid,
    `(None, ("data", "seq"))`, when B divides it — does zero redundant
    FLOPs but its boundary reshard moves a mesh sub-axis between tensor
    axes, which GSPMD's transpose meets with an involuntary full
    rematerialization (replicate + repartition) in EVERY backward pass
    (MULTICHIP_r02; still present through a two-step reshard). Until the
    Shardy partitioner handles that pattern, the replicated-scan layout is
    strictly faster end-to-end; `constrain_scan_inputs` keeps the two-step
    path for when a fully-sharded spec returns."""
    return (None, "data")


def data_sharding(mesh: Mesh, axis: int = 0, axis_name: str = "data") -> NamedSharding:
    """Shard the given array axis across the mesh's data axis."""
    spec = [None] * (axis + 1)
    spec[axis] = axis_name
    return NamedSharding(mesh, P(*spec))


def time_batch_sharding(
    mesh: Mesh, time_axis: int = 0, batch_axis: int = 1
) -> NamedSharding:
    """Sharding for `[..., T, ..., B, ...]` sequence batches: batch over
    "data" and — when the mesh has a "seq" axis — time over "seq" (the
    context-parallel input layout)."""
    spec = [None] * (max(time_axis, batch_axis) + 1)
    spec[batch_axis] = "data"
    if seq_axis_size(mesh) > 1:
        spec[time_axis] = "seq"
    return NamedSharding(mesh, P(*spec))


def shard_time_batch(
    tree: Any, mesh: Mesh, time_axis: int = 0, batch_axis: int = 1
) -> Any:
    """`shard_batch` for `[T, B, ...]` sequence data: batch always shards
    over "data"; time additionally shards over "seq" when present.

    Multi-host: each process contributes full-T, local-B data, so every seq
    group (a fixed data index, all seq indices) must live on ONE process —
    a seq axis spanning hosts would stitch unrelated per-host samples along
    time. `make_mesh` lays devices out process-major, so this holds whenever
    seq_devices divides the local device count; guard against the rest."""
    if jax.process_count() > 1 and seq_axis_size(mesh) > 1:
        for row in mesh.devices:  # fixed data index, varying seq
            if len({d.process_index for d in row}) != 1:
                raise ValueError(
                    "the seq mesh axis spans processes; pick seq_devices "
                    f"dividing the local device count ({jax.local_device_count()})"
                )
    return _put_sharded(tree, time_batch_sharding(mesh, time_axis, batch_axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _put_sharded(tree: Any, sharding: NamedSharding) -> Any:
    """One transfer per leaf, landing already distributed. Multi-host: each
    process passes its *local* shard and the result is a global array
    spanning the pod (the JAX-native replacement for the reference's
    DistributedSampler sharding, SURVEY.md §2.7)."""
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)),
            tree,
        )
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def shard_batch(tree: Any, mesh: Mesh, axis: int = 0, axis_name: str = "data") -> Any:
    """device_put a host batch with its `axis` sharded over the mesh."""
    return _put_sharded(tree, data_sharding(mesh, axis, axis_name))


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Replicate params across the mesh (the DDP 'same weights everywhere'
    invariant, enforced by sharding instead of broadcast)."""
    sharding = replicated_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
