"""Critical-path latency hiding for the interaction hot loop (ISSUE 4).

Round-3's phase attribution priced the remaining duty-vs-e2e gap in
synchronous host round-trips on the hot-loop critical path: the per-step
action device->host pull, the per-update replay-sample pull, and the
per-interval metric drain. This module removes them from the critical path
with three primitives — the single-chip analogue of the acting/learning/
data-movement overlap Podracer (arXiv:2104.06272) and MSRL
(arXiv:2210.00882) build with decoupled topologies:

  - :class:`ActionPipeline` — the policy-step jit's action indices start a
    `copy_to_host_async` the moment the jit returns (`dispatch`), and the
    blocking read happens only when `env.step` actually consumes them
    (`Handle.get`), so the d2h RTT overlaps JAX async dispatch and the
    host-side replay/bookkeeping work in between. Optionally one-step
    lagged (`lag=1`): the loop consumes action t-1 while step t's copy is
    still in flight, hiding the FULL RTT behind env compute — off-policy
    safe only (the executed action was computed from a one-step-stale
    observation; see howto/pipelining.md).
  - :class:`SamplePrefetcher` — double-buffers the replay sampler: when
    sample N is served, sample N+1's packed index put + device gather are
    dispatched immediately, so they execute while train step N runs. The
    epoch-consistency guard makes this bit-exact: a prefetched batch is
    served only if the ring has NOT been written since the prefetch
    (`buffer.epoch` unchanged, up to `max_staleness`); otherwise it is
    discarded and the sampler's PRNG state is REWOUND to what the prefetch
    consumed, so the fresh resample draws exactly the key the synchronous
    path would have — prefetched indices can never precede the rows they
    reference, and the on/off A/B trains on identical batches.
  - :class:`MetricDrain` — defers `MetricAggregator.compute()`'s blocking
    host pulls by one logging interval: at interval T the aggregator's
    pending device values are snapshotted and their async d2h copies
    issued; the blocking resolve happens at interval T+1, by which time
    the copies have long landed — logging costs zero synchronous round
    trips. Values are identical to eager compute (same floats, same step
    tags), they just reach the logger one interval later.

Every primitive has an `enabled=False` mode that IS the synchronous path
(same calls, same ordering), so call sites are identical under
`--pipeline on|off` and the equivalence receipts in
tests/test_parallel/test_pipeline.py compare the two modes directly.

Telemetry: construct via :meth:`Pipeline.from_args` and the per-primitive
stall/overlap gauges (`Pipeline/action_wait_ms`, `Pipeline/sample_hit_rate`,
...) ride the existing interval merge.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any

import numpy as np

__all__ = [
    "ActionPipeline",
    "MetricDrain",
    "Pipeline",
    "PipelineStats",
    "SamplePrefetcher",
]


def _start_copy(leaf: Any) -> Any:
    """Issue a non-blocking device->host copy for a jax array leaf; host
    values (numpy, python scalars) pass through untouched."""
    copy_async = getattr(leaf, "copy_to_host_async", None)
    if copy_async is not None:
        try:
            copy_async()
        # sheeplint: disable=SL012 — prefetch is a pure optimization; the
        # blocking read below is the correctness path and surfaces real errors
        except Exception:
            pass  # the blocking read in Handle.get still works
    return leaf


def _tree_map(fn, tree):
    import jax

    return jax.tree_util.tree_map(fn, tree)


class PipelineStats:
    """Shared counters behind the `Pipeline/*` telemetry gauges. `flush()`
    returns the per-interval gauge dict and zeroes the window."""

    def __init__(self) -> None:
        self.action_wait_s = 0.0
        self.action_fetches = 0
        self.sample_hits = 0
        self.sample_misses = 0
        self.sample_prefetches = 0
        self.metric_wait_s = 0.0
        self.metric_drains = 0

    def flush(self) -> dict[str, float]:
        out: dict[str, float] = {
            "Pipeline/action_wait_ms": 1e3 * self.action_wait_s,
            "Pipeline/action_fetches": float(self.action_fetches),
            "Pipeline/metric_drain_wait_ms": 1e3 * self.metric_wait_s,
        }
        served = self.sample_hits + self.sample_misses
        if served:
            out["Pipeline/sample_hit_rate"] = self.sample_hits / served
        out["Pipeline/sample_prefetches"] = float(self.sample_prefetches)
        self.__init__()
        return out


class _Handle:
    """One dispatched d2h copy; `get()` is the (accounted) blocking read."""

    __slots__ = ("_tree", "_stats")

    def __init__(self, tree, stats: PipelineStats | None):
        self._tree = tree
        self._stats = stats

    def get(self):
        t0 = time.perf_counter()
        out = _tree_map(np.asarray, self._tree)
        if self._stats is not None:
            self._stats.action_wait_s += time.perf_counter() - t0
            self._stats.action_fetches += 1
        return out


class ActionPipeline:
    """Split the policy-step d2h pull into dispatch (async copy starts) and
    read (blocking), so the RTT overlaps whatever host work runs in
    between. Disabled mode performs the same blocking conversion the
    synchronous loops always did — call sites are mode-agnostic."""

    def __init__(
        self, enabled: bool = True, lag: int = 0, stats: PipelineStats | None = None
    ):
        if lag < 0:
            raise ValueError(f"lag must be >= 0, got {lag}")
        self.enabled = enabled
        self.lag = lag
        self._stats = stats if stats is not None else PipelineStats()
        self._fifo: deque[_Handle] = deque()

    def dispatch(self, tree) -> _Handle:
        """Start the async device->host copies for every jax leaf and
        return a handle whose `get()` blocks on the (by then usually
        landed) transfer."""
        if self.enabled:
            _tree_map(_start_copy, tree)
        return _Handle(tree, self._stats if self.enabled else None)

    def fetch(self, tree):
        """dispatch + read in one call, honoring `lag`: with `lag=0` the
        returned host values are this step's (bit-exact vs the synchronous
        pull); with `lag=k`, the value dispatched k calls ago is returned
        and the first k calls return None (the caller primes with k extra
        policy steps, or falls back to a random action)."""
        if not self.enabled:
            return _tree_map(np.asarray, tree)
        handle = self.dispatch(tree)
        if self.lag == 0:
            return handle.get()
        self._fifo.append(handle)
        if len(self._fifo) <= self.lag:
            return None
        return self._fifo.popleft().get()

    def flush(self) -> list:
        """Drain any in-flight lagged entries (end of run)."""
        out = [h.get() for h in self._fifo]
        self._fifo.clear()
        return out


class SamplePrefetcher:
    """K=1 double-buffered replay sampler (see module docstring for the
    epoch-consistency guard). Wraps any buffer exposing `sample`; the
    guard and PRNG rewind engage when the buffer also exposes `epoch` and
    `get_sample_state`/`set_sample_state` (data/buffers.py) — without
    them every serve falls back to a fresh synchronous sample.

    `max_staleness` (buffer epochs) > 0 opts into serving prefetched
    batches across ring writes: the batch is a consistent snapshot of the
    ring at prefetch time (device gathers capture the store at dispatch),
    but the newest `<= max_staleness` writes are not sampleable — an
    off-policy-only relaxation (howto/pipelining.md)."""

    def __init__(
        self,
        rb,
        enabled: bool = True,
        max_staleness: int = 0,
        stats: PipelineStats | None = None,
    ):
        self._rb = rb
        # host/memmap rings gather synchronously on host — prefetching
        # would do the same blocking work one call early for no overlap
        self.enabled = enabled and getattr(rb, "is_device_backed", False)
        self.max_staleness = max_staleness
        self._stats = stats if stats is not None else PipelineStats()
        self._pre: tuple | None = None  # (sig, epoch, prng_state, batch)
        self._last_epoch: int | None = None  # epoch at the previous serve

    def __getattr__(self, name):  # delegate everything else to the buffer
        return getattr(self._rb, name)

    def sample(self, *args, **kwargs):
        rb = self._rb
        if not self.enabled:
            return rb.sample(*args, **kwargs)
        sig = (args, tuple(sorted(kwargs.items())))
        batch = None
        if self._pre is not None:
            p_sig, p_epoch, p_state, p_batch = self._pre
            self._pre = None
            epoch = getattr(rb, "epoch", None)
            fresh = (
                p_sig == sig
                and p_epoch is not None
                and epoch is not None
                and epoch - p_epoch <= self.max_staleness
            )
            if fresh:
                self._stats.sample_hits += 1
                batch = p_batch
            else:
                # epoch-consistency guard: the ring advanced (or the call
                # signature changed) since the prefetch — discard it and
                # REWIND the sampler's PRNG to the state the prefetch
                # consumed, so the fresh resample draws the same key the
                # synchronous path would have (bit-exact on/off A/B) and
                # samples against the rows that now exist
                self._stats.sample_misses += 1
                if p_state is not None:
                    try:
                        rb.set_sample_state(p_state)
                    # sheeplint: disable=SL012 — best-effort PRNG rewind after a
                    # discarded prefetch; the miss is already counted in
                    # sample_misses and the fresh resample is correct either way
                    except Exception:
                        pass
        if batch is None:
            batch = rb.sample(*args, **kwargs)
        # dispatch the NEXT sample now — its packed index put + device
        # gather execute while the caller's train step runs — but only when
        # it can plausibly hit: a discarded prefetch still paid its put +
        # gather, so in write-every-gap loops (epoch advanced between the
        # last two serves, strict staleness) prefetching is paused until a
        # quiet gap re-arms it (e.g. the multi-sample pretrain/catch-up
        # bursts, where it then hits every call)
        epoch_now = getattr(rb, "epoch", None)
        predict_quiet = (
            self.max_staleness > 0
            or self._last_epoch is None
            or epoch_now is None
            or epoch_now == self._last_epoch
        )
        self._last_epoch = epoch_now
        if predict_quiet:
            try:
                state = (
                    rb.get_sample_state() if hasattr(rb, "get_sample_state") else None
                )
                pre_batch = rb.sample(*args, **kwargs)
                self._pre = (sig, epoch_now, state, pre_batch)
                self._stats.sample_prefetches += 1
            except Exception:
                self._pre = None
        return batch


class MetricDrain:
    """Deferred metric resolution: `drain(agg, step)` returns the PREVIOUS
    interval's `(metrics, step)` pairs (whose d2h copies were issued one
    interval ago and have landed) and snapshots + resets the current one.
    Disabled mode computes eagerly — identical to the pre-pipeline loops.
    Call `flush()` after the training loop to resolve the final snapshot."""

    def __init__(self, enabled: bool = True, stats: PipelineStats | None = None):
        self.enabled = enabled
        self._stats = stats if stats is not None else PipelineStats()
        self._pending: tuple | None = None  # (PendingMetrics, step)

    def drain(self, aggregator, step: int) -> list[tuple[dict, int]]:
        if not self.enabled:
            out = [(aggregator.compute(), step)]
            aggregator.reset()
            return out
        out = []
        if self._pending is not None:
            snap, s = self._pending
            t0 = time.perf_counter()
            out.append((snap.resolve(), s))
            self._stats.metric_wait_s += time.perf_counter() - t0
            self._stats.metric_drains += 1
        self._pending = (aggregator.snapshot(), step)
        aggregator.reset()
        return out

    def flush(self) -> list[tuple[dict, int]]:
        if self._pending is None:
            return []
        snap, s = self._pending
        self._pending = None
        return [(snap.resolve(), s)]


class Pipeline:
    """Facade the algorithm mains construct once: `.action` (the d2h
    pipeline), `.sampler(rb)` (the prefetching wrapper), and
    `.drain_metrics` / `.flush_metrics` (the deferred drain). With
    `--pipeline off` every member runs the exact synchronous path, so the
    mains carry ONE code path for both modes."""

    def __init__(
        self, enabled: bool = False, lag: int = 0, max_staleness: int = 0
    ):
        self.enabled = enabled
        self.max_staleness = max_staleness
        self.stats = PipelineStats()
        self.action = ActionPipeline(enabled, lag=lag, stats=self.stats)
        self._drain = MetricDrain(enabled, stats=self.stats)
        self._samplers: dict[int, SamplePrefetcher] = {}

    @classmethod
    def from_args(cls, args, telem=None) -> "Pipeline":
        """The mains' shared construction helper: `--pipeline on` enables
        all three primitives (bit-exact defaults: lag=0, strict epoch
        guard); SHEEPRL_TPU_PIPELINE_STALENESS opts into the off-policy
        staleness relaxation. Registers the `Pipeline/*` gauges on the
        run's Telemetry when enabled."""
        enabled = str(getattr(args, "pipeline", "off")) == "on"
        staleness = int(os.environ.get("SHEEPRL_TPU_PIPELINE_STALENESS", "0"))
        pipe = cls(enabled=enabled, max_staleness=staleness)
        if telem is not None and enabled:
            telem.add_gauges(pipe.gauges)
        return pipe

    def sampler(self, rb) -> SamplePrefetcher:
        """The prefetching wrapper for `rb`, cached per buffer instance so
        call sites may use `pipe.sampler(rb).sample(...)` inline — the
        double-buffer state persists across calls."""
        wrapper = self._samplers.get(id(rb))
        if wrapper is None or wrapper._rb is not rb:
            wrapper = SamplePrefetcher(
                rb, enabled=self.enabled, max_staleness=self.max_staleness,
                stats=self.stats,
            )
            self._samplers[id(rb)] = wrapper
        return wrapper

    def drain_metrics(self, aggregator, step: int) -> list[tuple[dict, int]]:
        return self._drain.drain(aggregator, step)

    def flush_metrics(self) -> list[tuple[dict, int]]:
        return self._drain.flush()

    def gauges(self) -> dict[str, float]:
        return self.stats.flush()
