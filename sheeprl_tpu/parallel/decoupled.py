"""Decoupled player/trainer topology over device sub-meshes.

The TPU-native replacement for the reference's process-based decoupling
(/root/reference/sheeprl/algos/ppo/ppo_decoupled.py:534-581: rank-0 player,
ranks 1..N DDP trainers, pickled-TensorDict `scatter_object_list` for data
and a flattened-parameter broadcast for weights). Here both roles live in
one SPMD program over DISJOINT sub-meshes of the same device set:

  - the PLAYER owns the envs and runs policy inference on its own device;
  - the TRAINERS run the jitted update with the batch sharded over the
    trainer mesh's data axis (XLA inserts the gradient all-reduce);
  - the data path is a typed pytree `device_put` onto the trainer sharding
    (device-to-device over ICI — replacing the pickled object scatter);
  - the weight path is a pytree `device_put` of the updated params back to
    the player device (replacing `parameters_to_vector`/broadcast,
    ppo_decoupled.py:152-160);
  - no shutdown sentinel is needed (single program, one control flow), and
    uneven inputs cannot arise (batches are statically sharded), replacing
    the reference's `Join` context (ppo_decoupled.py:439).

Multi-host: the same construction over `jax.devices()` spanning the pod
puts the player on host-0's first device and shards trainers across the
rest; the `device_put`s ride ICI/DCN.
"""

from __future__ import annotations

import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import local_mesh_devices

__all__ = ["DecoupledMeshes", "make_decoupled_meshes"]


def _default_deadline() -> float | None:
    """Weight-transfer deadline (seconds) from SHEEPRL_TPU_TRANSFER_TIMEOUT_S;
    None (unset/non-positive) disables the graceful-degradation path."""
    raw = os.environ.get("SHEEPRL_TPU_TRANSFER_TIMEOUT_S")
    if not raw:
        return None
    val = float(raw)
    return val if val > 0 else None


class DecoupledMeshes:
    """Player device + trainer mesh with the data/weight transfer helpers.

    The transfer paths keep telemetry counters (ISSUE 2): data/weight
    transfer counts and byte volumes, plus the weight pipeline's
    queue-depth (weight versions shipped to the player but not yet swapped
    in) and staleness (updates the player's current weights are behind the
    trainers) — the numbers that tell an overlapped run whether its player
    is starving or training on ancient policies. Mains surface them by
    registering `telemetry_gauges` with their Telemetry instance and calling
    `note_weights_applied()` where they swap a landed transfer in."""

    def __init__(self, player_device, trainer_mesh: Mesh):
        self.player_device = player_device
        self.trainer_mesh = trainer_mesh
        self._to_trainer_transfers = 0
        self._to_trainer_bytes = 0
        self._to_player_transfers = 0
        self._to_player_bytes = 0
        self._weights_shipped = 0
        self._weights_applied = 0
        self._last_applied_ts: float | None = None

    @property
    def num_trainers(self) -> int:
        return self.trainer_mesh.devices.size

    def to_trainers(self, batch: Any, axis: int = 0) -> Any:
        """Ship a batch pytree onto the trainer mesh, sharded on `axis` —
        the rollout/replay-sample data path (replacing
        `scatter_object_list`, ppo_decoupled.py:294-297). When `axis` is not
        divisible by the trainer count it is padded by wrapping around, the
        same semantics as the reference's DistributedSampler padding."""
        spec = [None] * (axis + 1)
        spec[axis] = "data"
        sharding = NamedSharding(self.trainer_mesh, P(*spec))
        n = self.num_trainers

        def put(x):
            size = x.shape[axis]
            rem = size % n
            if rem:
                idx = [slice(None)] * x.ndim
                idx[axis] = np.arange(size, size + n - rem) % size
                x = jnp.concatenate([x, x[tuple(idx)]], axis=axis)
            self._to_trainer_bytes += getattr(x, "nbytes", 0)
            return jax.device_put(x, sharding)

        self._to_trainer_transfers += 1
        return jax.tree_util.tree_map(put, batch)

    def replicated_on_trainers(self, tree: Any) -> Any:
        """Replicate params across the trainer mesh (the trainer DDP
        invariant)."""
        sharding = NamedSharding(self.trainer_mesh, P())
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)

    def to_player(self, tree: Any, deadline_s: float | None = None) -> Any:
        """Ship (updated) params to the player device — the weight path
        (replacing the flattened-vector broadcast, ppo_decoupled.py:304-307).

        Graceful degradation (ISSUE 12): when the transfer exceeds
        `deadline_s` (default: SHEEPRL_TPU_TRANSFER_TIMEOUT_S, off when
        unset), the shipment is ABANDONED and None is returned — the caller
        keeps acting on its current (stale) weights instead of deadlocking
        the env loop behind a sick interconnect; the existing
        `Decoupled/weight_staleness_s` gauge shows the growing lag and
        `Fault/transfer_timeouts` counts the abandonments. The deterministic
        `transfer.stall@n[:seconds]` injection site models the sick link:
        the n-th weight transfer sleeps before shipping."""
        from ..resilience import inject

        if deadline_s is None:
            deadline_s = _default_deadline()
        start = time.monotonic()
        spec = inject.get_plan().fire_next("transfer.stall")
        if spec is not None:
            time.sleep(spec.param if spec.param is not None else 1.0)
        self._to_player_transfers += 1
        self._weights_shipped += 1

        def put(x):
            self._to_player_bytes += getattr(x, "nbytes", 0)
            return jax.device_put(x, self.player_device)

        out = jax.tree_util.tree_map(put, tree)
        if deadline_s is not None and (time.monotonic() - start) > deadline_s:
            self._weights_applied = self._weights_shipped  # not pending: dropped
            inject.note_recovery(
                "transfer.stall",
                "transfer_timeouts",
                elapsed_s=round(time.monotonic() - start, 3),
                deadline_s=deadline_s,
            )
            return None
        return out

    def note_weights_applied(self) -> None:
        """Record that the player swapped in the most recent landed weight
        transfer: staleness is measured against versions shipped SINCE."""
        self._weights_applied = self._weights_shipped
        self._last_applied_ts = time.monotonic()

    def telemetry_gauges(self) -> dict[str, float]:
        """Queue-depth/staleness + transfer-volume gauges for Telemetry
        (`telem.add_gauges(meshes.telemetry_gauges)`)."""
        return {
            "Decoupled/data_transfers": float(self._to_trainer_transfers),
            "Decoupled/data_mb_total": self._to_trainer_bytes / 2**20,
            "Decoupled/weight_transfers": float(self._to_player_transfers),
            "Decoupled/weight_mb_total": self._to_player_bytes / 2**20,
            # weight versions in flight: shipped to the player but not yet
            # swapped in (a growing queue means the player never catches up)
            "Decoupled/weight_queue_depth": float(
                self._weights_shipped - self._weights_applied
            ),
            # wall-clock age of the player's current weights (seconds since
            # the last swap; 0.0 until the first swap happens)
            "Decoupled/weight_staleness_s": (
                0.0
                if self._last_applied_ts is None
                else time.monotonic() - self._last_applied_ts
            ),
        }


def make_decoupled_meshes(
    num_devices: int = -1, platform: str | None = None
) -> DecoupledMeshes:
    """First device -> player, the rest -> trainer mesh. Like the reference
    (which requires >= 2 torchrun ranks, ppo_decoupled.py:545-551), the
    topology needs at least 2 devices."""
    devices = local_mesh_devices(num_devices, platform)
    if len(devices) < 2:
        raise RuntimeError(
            f"decoupled player/trainer topology requires at least 2 devices, "
            f"got {len(devices)}; run the coupled task instead"
        )
    trainer_mesh = Mesh(np.asarray(devices[1:]), ("data",))
    return DecoupledMeshes(player_device=devices[0], trainer_mesh=trainer_mesh)
