from .decoupled import DecoupledMeshes, make_decoupled_meshes
from .mesh import (
    assert_divisible,
    data_sharding,
    distributed_setup,
    local_mesh_devices,
    make_constrain,
    make_mesh,
    process_index,
    replicate,
    replicated_sharding,
    seq_axis_size,
    shard_batch,
    shard_time_batch,
    time_batch_sharding,
)

__all__ = [
    "DecoupledMeshes",
    "assert_divisible",
    "data_sharding",
    "distributed_setup",
    "local_mesh_devices",
    "make_decoupled_meshes",
    "make_constrain",
    "make_mesh",
    "process_index",
    "replicate",
    "replicated_sharding",
    "seq_axis_size",
    "shard_batch",
    "shard_time_batch",
    "time_batch_sharding",
]
