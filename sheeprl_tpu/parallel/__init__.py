from .decoupled import DecoupledMeshes, make_decoupled_meshes
from .mesh import (
    assert_divisible,
    data_sharding,
    distributed_setup,
    local_mesh_devices,
    make_mesh,
    process_index,
    replicate,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "DecoupledMeshes",
    "assert_divisible",
    "data_sharding",
    "distributed_setup",
    "local_mesh_devices",
    "make_decoupled_meshes",
    "make_mesh",
    "process_index",
    "replicate",
    "replicated_sharding",
    "shard_batch",
]
