"""Anakin runtime helpers: env-batch sharding + collection telemetry.

The Podracer Anakin arrangement replicates the policy over the mesh and
shards the *environment batch* across it — each device steps its slice of
the envs and runs its slice of the policy, with zero cross-device traffic
inside the rollout scan (the gradient all-reduce in the update step is the
only collective). `shard_env_batch` places a collector carry (or any
pytree of `[N, ...]` leaves) accordingly; leaves whose leading dim does not
divide the mesh (PRNG keys, scalars) are replicated.

`AnakinStats` is the `Anakin/*` gauge source every wired main registers
with its Telemetry: collection rate, scan span, env batch and device count
— the numbers `bench.py --algo anakin` prices."""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["AnakinStats", "shard_env_batch"]


def shard_env_batch(tree: Any, mesh: Mesh, axis_name: str = "data") -> Any:
    """Shard every `[N, ...]` leaf of `tree` over the mesh's `axis_name`
    (leading axis = env batch); anything that doesn't divide is replicated.
    A no-op commit on 1-device meshes — the arrays still become committed,
    so `CompilePlan` shape capture records the layout the live calls use."""
    n_dev = mesh.shape[axis_name]

    def one(x):
        if hasattr(x, "ndim") and x.ndim >= 1 and x.shape[0] % n_dev == 0:
            spec = P(axis_name)
        else:
            spec = P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(one, tree)


class AnakinStats:
    """Collection-side counters for the fully-jitted rollout path.

    Usage in a main:

        anakin = AnakinStats(scan_span=T, env_batch=N, devices=n_dev)
        telem.add_gauges(anakin.gauges)
        ...
        t0 = time.perf_counter()
        carry, traj, ep = collect(...)   # jitted rollout
        jax.block_until_ready(traj)      # honest rate: scan fully retired
        anakin.note(T * N, time.perf_counter() - t0)
    """

    def __init__(self, scan_span: int, env_batch: int, devices: int):
        self.scan_span = int(scan_span)
        self.env_batch = int(env_batch)
        self.devices = int(devices)
        self.rollouts = 0
        self.env_steps_total = 0
        self.collect_seconds_total = 0.0
        self._last_sps = 0.0

    def note(self, env_steps: int, seconds: float) -> None:
        self.rollouts += 1
        self.env_steps_total += int(env_steps)
        self.collect_seconds_total += float(seconds)
        if seconds > 0:
            self._last_sps = env_steps / seconds

    @property
    def env_steps_per_second(self) -> float:
        return self._last_sps

    def gauges(self) -> dict[str, float]:
        """`Anakin/*` gauge source for `Telemetry.add_gauges`."""
        out = {
            "Anakin/env_steps_per_second": self._last_sps,
            "Anakin/scan_span": float(self.scan_span),
            "Anakin/env_batch": float(self.env_batch),
            "Anakin/devices": float(self.devices),
            "Anakin/rollouts": float(self.rollouts),
            "Anakin/env_steps_total": float(self.env_steps_total),
            "Anakin/collect_seconds_total": self.collect_seconds_total,
        }
        if self.collect_seconds_total > 0:
            out["Anakin/env_steps_per_second_avg"] = (
                self.env_steps_total / self.collect_seconds_total
            )
        return out
