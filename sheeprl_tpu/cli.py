"""CLI launcher: `python -m sheeprl_tpu <task> [--flags]`.

The reference's click-group + torchrun self-spawn machinery
(/root/reference/sheeprl/cli.py:19-90) collapses here: JAX is SPMD —
one process drives all local devices, so decoupled (player/trainer)
topologies run as sub-meshes of a single program instead of torchrun
process groups. Multi-host pods launch one process per host externally and
call `jax.distributed.initialize` (see sheeprl_tpu/parallel/mesh.py).
"""

from __future__ import annotations

import sys

from .utils.registry import decoupled_tasks, tasks


def _print_usage() -> None:
    print("usage: sheeprl_tpu <task> [--flags] | sheeprl_tpu --help")
    print("\navailable tasks:")
    for name in sorted(tasks):
        kind = " (decoupled)" if name in decoupled_tasks else ""
        print(f"  {name}{kind}")


def run(argv: list[str] | None = None) -> None:
    from . import algos  # noqa: F401 -- imports fire @register_algorithm

    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        _print_usage()
        return
    task = argv[0]
    if task not in tasks:
        print(f"unknown task {task!r}", file=sys.stderr)
        _print_usage()
        raise SystemExit(2)
    tasks[task](argv[1:])
