"""Recurrent cells: GRU (plain + LayerNorm/Hafner variant) and LSTM.

These are the sequence workhorses of the framework — the reference has no
attention anywhere; its sequence models are a LayerNorm-GRU (DreamerV1-3,
/root/reference/sheeprl/models/models.py:330-402) and an LSTM (recurrent PPO,
/root/reference/sheeprl/algos/ppo_recurrent/agent.py:41). Cells here are
single-step pure functions designed to be the body of `jax.lax.scan` over
time, with batch sharded across the device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .core import Module, static
from .layers import LayerNorm, Linear

__all__ = ["GRUCell", "LayerNormGRUCell", "LSTMCell", "scan_cell"]


class GRUCell(Module):
    """Standard (textbook / torch.nn.GRUCell) GRU: the reset gate scales only
    the hidden-state contribution of the candidate,
    `n = tanh(W_in x + r * (W_hn h))`."""

    input_proj: Linear  # [in, 3*hidden]
    hidden_proj: Linear  # [hidden, 3*hidden]
    hidden_size: int = static()

    @classmethod
    def init(cls, key, input_size: int, hidden_size: int, *, use_bias: bool = True):
        k1, k2 = jax.random.split(key)
        input_proj = Linear.init(k1, input_size, 3 * hidden_size, use_bias=use_bias)
        hidden_proj = Linear.init(k2, hidden_size, 3 * hidden_size, use_bias=use_bias)
        return cls(input_proj=input_proj, hidden_proj=hidden_proj, hidden_size=hidden_size)

    def __call__(self, x: jax.Array, h: jax.Array) -> jax.Array:
        xi_r, xi_z, xi_n = jnp.split(self.input_proj(x), 3, axis=-1)
        hh_r, hh_z, hh_n = jnp.split(self.hidden_proj(h), 3, axis=-1)
        r = jax.nn.sigmoid(xi_r + hh_r)
        z = jax.nn.sigmoid(xi_z + hh_z)
        n = jnp.tanh(xi_n + r * hh_n)
        return (1.0 - z) * n + z * h


class LayerNormGRUCell(Module):
    """GRU with LayerNorm on the fused projection and the `sigmoid(u - 1)`
    update-gate bias trick — the DreamerV2/V3 recurrence
    (/root/reference/sheeprl/models/models.py:330-402). The fused
    [x,h] @ W projection is a single MXU matmul; the gate math is elementwise
    and fuses into it under XLA."""

    proj: Linear
    norm: LayerNorm | None
    hidden_size: int = static()

    @classmethod
    def init(
        cls,
        key,
        input_size: int,
        hidden_size: int,
        *,
        layer_norm: bool = True,
        use_bias: bool = False,
    ):
        proj = Linear.init(key, input_size + hidden_size, 3 * hidden_size, use_bias=use_bias)
        norm = LayerNorm.init(3 * hidden_size) if layer_norm else None
        return cls(proj=proj, norm=norm, hidden_size=hidden_size)

    def __call__(self, x: jax.Array, h: jax.Array) -> jax.Array:
        from ..ops.pallas_kernels import layernorm_gru_cell, use_pallas

        if (
            use_pallas("gru")
            and self.norm is not None
            and self.norm.scale is not None
            and self.proj.bias is None
            and x.ndim == 2
        ):
            return layernorm_gru_cell(
                x,
                h,
                # weights follow the input dtype (bf16 compute with f32
                # master params, like the plain-XLA Linear path); LN affine
                # params stay f32 — the kernel normalizes in f32 regardless
                self.proj.weight.astype(x.dtype),
                self.norm.scale,
                self.norm.offset,
                self.norm.eps,
            )
        parts = self.proj(jnp.concatenate([x, h], axis=-1))
        if self.norm is not None:
            parts = self.norm(parts)
        r, c, u = jnp.split(parts, 3, axis=-1)
        reset = jax.nn.sigmoid(r)
        cand = jnp.tanh(reset * c)
        update = jax.nn.sigmoid(u - 1.0)
        return update * cand + (1.0 - update) * h


class LSTMCell(Module):
    """Standard LSTM cell; state is an (h, c) tuple."""

    proj: Linear  # [in+hidden, 4*hidden]
    hidden_size: int = static()

    @classmethod
    def init(cls, key, input_size: int, hidden_size: int, *, use_bias: bool = True):
        proj = Linear.init(key, input_size + hidden_size, 4 * hidden_size, use_bias=use_bias)
        return cls(proj=proj, hidden_size=hidden_size)

    def __call__(self, x: jax.Array, state: tuple[jax.Array, jax.Array]):
        h, c = state
        parts = self.proj(jnp.concatenate([x, h], axis=-1))
        i, f, g, o = jnp.split(parts, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f + 1.0), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def initial_state(self, batch_shape: tuple[int, ...]) -> tuple[jax.Array, jax.Array]:
        z = jnp.zeros(batch_shape + (self.hidden_size,))
        return z, z


def scan_cell(cell, xs: jax.Array, h0, *, reset_mask: jax.Array | None = None):
    """Run a cell over time with `lax.scan`.

    xs: [T, B, D] inputs; h0: initial state pytree; reset_mask: optional
    [T, B] bool/float — where True the state is zeroed *before* the step
    (the `is_first` semantics of the Dreamer RSSM,
    /root/reference/sheeprl/algos/dreamer_v3/agent.py:373-378).
    Returns (final_state, stacked_outputs [T, B, H]).
    """

    def step(h, inp):
        if reset_mask is None:
            x = inp
        else:
            x, m = inp
            # keep the reset arithmetic in each state leaf's dtype — a f32
            # mask would promote a bf16 carry and destabilize the scan
            m = m[..., None]
            h = jax.tree_util.tree_map(
                lambda s: s * (1.0 - m.astype(s.dtype)), h
            )
        out = cell(x, h)
        # GRU cells return the new state directly; LSTM returns (out, state)
        if isinstance(out, tuple):
            y, h_new = out
        else:
            y, h_new = out, out
        return h_new, y

    inputs = xs if reset_mask is None else (xs, reset_mask)
    return jax.lax.scan(step, h0, inputs)
