"""Composite blocks: MLP, CNN, DeCNN, NatureCNN, MultiEncoder/MultiDecoder.

Functional equivalents of the reference's miniblock machinery
(/root/reference/sheeprl/models/models.py:15-327, utils/model.py:24-222):
each block is a stack of (linear|conv) -> norm -> activation [-> dropout]
miniblocks. Dropout is pure (keys threaded explicitly).
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp

from .core import Activation, Module, activation, static
from .layers import Conv2d, ConvTranspose2d, LayerNorm, Linear, dropout

__all__ = ["MLP", "CNN", "DeCNN", "NatureCNN", "MultiEncoder", "MultiDecoder"]


def _split(key, n):
    return jax.random.split(key, n) if n > 0 else []


def _fold_rows(x: jax.Array):
    """Fold leading dims of `[lead..., H, W, C]` into conv rows; returns the
    rows and the inverse. A `[T, B]` sequence batch folds BATCH-major: under
    context parallelism the input is `("seq", "data")`-sharded, and batch-
    major rows are contiguously sharded over the full mesh grid
    (`P(("data", "seq"))`) so the convs parallelize over every device — the
    time-major fold interleaves the shards, which GSPMD can only represent
    by replicating the convs over "data" (observed in the dp x sp DV3 step,
    round 3). The swap is sharding metadata plus a local relayout; numerics
    are unchanged (each (t, b) row maps through the same convolution)."""
    lead = x.shape[:-3]
    if len(lead) == 2:
        x = jnp.swapaxes(x, 0, 1)
    rows = x.reshape((-1,) + x.shape[-3:])

    def unfold(y: jax.Array) -> jax.Array:
        if len(lead) == 2:
            t, b = lead
            return jnp.swapaxes(y.reshape((b, t) + y.shape[1:]), 0, 1)
        return y.reshape(lead + y.shape[1:])

    return rows, unfold


class MLP(Module):
    """Linear stack with optional per-layer LayerNorm / dropout and output head.

    Mirrors the capability of the reference MLP
    (/root/reference/sheeprl/models/models.py:15-118): hidden miniblocks are
    Linear -> [dropout] -> [LayerNorm] -> act (the reference miniblock order,
    utils/model.py:70-87 — the DroQ-paper critic layout); the optional output
    head is a bare Linear.
    """

    layers: tuple[Linear, ...]
    norms: tuple[LayerNorm | None, ...]
    head: Linear | None
    act: Activation = static(default="tanh")
    dropout_rate: float = static(default=0.0)

    @classmethod
    def init(
        cls,
        key,
        input_dim: int,
        hidden_sizes: Sequence[int],
        output_dim: int | None = None,
        *,
        act: Activation = "tanh",
        layer_norm: bool = False,
        dropout_rate: float = 0.0,
        use_bias: bool = True,
        norm_eps: float = 1e-5,
    ):
        sizes = [input_dim, *hidden_sizes]
        keys = _split(key, len(hidden_sizes) + 1)
        layers = tuple(
            Linear.init(k, sizes[i], sizes[i + 1], use_bias=use_bias)
            for i, k in enumerate(keys[: len(hidden_sizes)])
        )
        norms = tuple(
            LayerNorm.init(s, eps=norm_eps) if layer_norm else None for s in sizes[1:]
        )
        head = None
        if output_dim is not None:
            head = Linear.init(keys[-1], sizes[-1], output_dim)
        return cls(
            layers=layers, norms=norms, head=head, act=act, dropout_rate=dropout_rate
        )

    def __call__(self, x: jax.Array, *, key=None, training: bool = False):
        act = activation(self.act)
        for i, layer in enumerate(self.layers):
            x = layer(x)
            if self.dropout_rate > 0.0 and training and key is not None:
                key, sub = jax.random.split(key)
                x = dropout(sub, x, self.dropout_rate)
            if self.norms[i] is not None:
                x = self.norms[i](x)
            x = act(x)
        if self.head is not None:
            x = self.head(x)
        return x

    @property
    def output_dim(self) -> int:
        if self.head is not None:
            return self.head.out_features
        return self.layers[-1].out_features


class CNN(Module):
    """Conv2d stack (NHWC): conv -> [LayerNorm over channels] -> act."""

    layers: tuple[Conv2d, ...]
    norms: tuple[LayerNorm | None, ...]
    act: Activation = static(default="relu")

    @classmethod
    def init(
        cls,
        key,
        in_channels: int,
        channels: Sequence[int],
        kernel_sizes: Sequence[int],
        strides: Sequence[int],
        *,
        paddings: Sequence[str | int] | None = None,
        act: Activation = "relu",
        layer_norm: bool = False,
        use_bias: bool = True,
        norm_eps: float = 1e-5,
    ):
        n = len(channels)
        if paddings is None:
            paddings = ["SAME"] * n
        chans = [in_channels, *channels]
        keys = _split(key, n)
        layers = tuple(
            Conv2d.init(
                keys[i],
                chans[i],
                chans[i + 1],
                kernel_sizes[i],
                stride=strides[i],
                padding=paddings[i],
                use_bias=use_bias,
            )
            for i in range(n)
        )
        norms = tuple(
            LayerNorm.init(c, eps=norm_eps) if layer_norm else None for c in channels
        )
        return cls(layers=layers, norms=norms, act=act)

    def __call__(self, x: jax.Array) -> jax.Array:
        """x: [..., H, W, C] — leading batch dims are folded around the convs
        (batch-major for sequence batches, see _fold_rows)."""
        from ..ops import pallas_cnn

        x, unfold = _fold_rows(x)
        act = activation(self.act)
        for i, layer in enumerate(self.layers):
            norm = self.norms[i]
            if (
                norm is not None
                and norm.scale is not None
                and layer.bias is None
                # even spatial dims only: the kernel computes h//2 while the
                # XLA SAME path computes ceil(h/2) — odd inputs (e.g. the
                # 21x21 stage of an 84x84 encoder) must stay unfused or the
                # toggle would change output shapes
                and x.shape[-3] % 2 == 0
                and x.shape[-2] % 2 == 0
                and pallas_cnn.cnn_stage_supported(
                    layer.kernel.shape, layer.stride, layer.padding, True, self.act
                )
            ):
                # fused Dreamer miniblock: conv + LayerNorm + SiLU in one
                # Pallas kernel (ops/pallas_cnn.py)
                x = pallas_cnn.conv_ln_silu(
                    x, layer.kernel.astype(x.dtype), norm.scale, norm.offset,
                    norm.eps,
                )
                continue
            x = layer(x)
            if norm is not None:
                x = norm(x)
            x = act(x)
        return unfold(x)


class DeCNN(Module):
    """ConvTranspose2d stack (NHWC). By default the last layer has no
    norm/activation (decoder-output convention); `act_last=True` activates
    every layer like the reference DeCNN (models.py:204-287), for use as an
    inner trunk (e.g. the SAC-AE decoder)."""

    layers: tuple[ConvTranspose2d, ...]
    norms: tuple[LayerNorm | None, ...]
    act: Activation = static(default="relu")
    act_last: bool = static(default=False)

    @classmethod
    def init(
        cls,
        key,
        in_channels: int,
        channels: Sequence[int],
        kernel_sizes: Sequence[int],
        strides: Sequence[int],
        *,
        paddings: Sequence[str | int] | None = None,
        act: Activation = "relu",
        layer_norm: bool = False,
        use_bias: bool = True,
        act_last: bool = False,
        norm_eps: float = 1e-5,
    ):
        n = len(channels)
        if paddings is None:
            paddings = ["SAME"] * n
        chans = [in_channels, *channels]
        keys = _split(key, n)
        layers = tuple(
            ConvTranspose2d.init(
                keys[i],
                chans[i],
                chans[i + 1],
                kernel_sizes[i],
                stride=strides[i],
                padding=paddings[i],
                use_bias=use_bias,
            )
            for i in range(n)
        )
        # norm/act after the final deconv only when act_last
        norms = tuple(
            LayerNorm.init(c, eps=norm_eps)
            if (layer_norm and (act_last or i < n - 1))
            else None
            for i, c in enumerate(channels)
        )
        return cls(layers=layers, norms=norms, act=act, act_last=act_last)

    def __call__(self, x: jax.Array) -> jax.Array:
        """x: [..., H, W, C] latent grid -> [..., H', W', C'] image
        (leading dims folded batch-major, see _fold_rows)."""
        from ..ops import pallas_cnn

        x, unfold = _fold_rows(x)
        act = activation(self.act)
        last = len(self.layers) - 1
        for i, layer in enumerate(self.layers):
            norm = self.norms[i]
            if (
                norm is not None
                and norm.scale is not None
                and layer.bias is None
                and (i != last or self.act_last)
                and pallas_cnn.cnn_stage_supported(
                    layer.kernel.shape, layer.stride, layer.padding, True, self.act
                )
            ):
                # fused subpixel-deconv + LayerNorm + SiLU Pallas stage
                x = pallas_cnn.deconv_ln_silu(
                    x, layer.kernel.astype(x.dtype), norm.scale, norm.offset,
                    norm.eps,
                )
                continue
            x = layer(x)
            if norm is not None:
                x = norm(x)
            if i != last or self.act_last:
                x = act(x)
        return unfold(x)


class NatureCNN(Module):
    """DQN-Nature encoder (3 convs + fc), NHWC
    (/root/reference/sheeprl/models/models.py:287-327)."""

    cnn: CNN
    fc: Linear
    act: Activation = static(default="relu")

    @classmethod
    def init(
        cls,
        key,
        in_channels: int,
        features_dim: int,
        *,
        screen_size: int = 64,
        channels_multiplier: int = 1,
    ):
        if channels_multiplier <= 0:
            raise ValueError(
                f"channels_multiplier must be greater than zero, given {channels_multiplier}"
            )
        ckey, fkey = jax.random.split(key)
        cnn = CNN.init(
            ckey,
            in_channels,
            channels=[32 * channels_multiplier, 64 * channels_multiplier, 64 * channels_multiplier],
            kernel_sizes=[8, 4, 3],
            strides=[4, 2, 1],
            paddings=["VALID"] * 3,
            act="relu",
        )
        # probe the flattened conv output size without running real compute
        probe = jax.eval_shape(
            cnn, jax.ShapeDtypeStruct((1, screen_size, screen_size, in_channels), jnp.float32)
        )
        flat = math.prod(probe.shape[1:])
        fc = Linear.init(fkey, flat, features_dim)
        return cls(cnn=cnn, fc=fc)

    def __call__(self, x: jax.Array) -> jax.Array:
        lead = x.shape[:-3]
        y = self.cnn(x)
        y = y.reshape(lead + (-1,))
        return activation(self.act)(self.fc(y))

    @property
    def output_dim(self) -> int:
        return self.fc.out_features


class MultiEncoder(Module):
    """Fuse a CNN encoder (over channel-concatenated image keys) and an MLP
    encoder (over feature-concatenated vector keys) of a dict observation
    (/root/reference/sheeprl/models/models.py:405-460). Either may be None."""

    cnn_encoder: Module | None
    mlp_encoder: Module | None
    cnn_keys: tuple[str, ...] = static(default=())
    mlp_keys: tuple[str, ...] = static(default=())

    def __call__(self, obs: dict, **kwargs) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None:
            cnn_in = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-1)
            feats.append(self.cnn_encoder(cnn_in))
        if self.mlp_encoder is not None:
            mlp_in = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            feats.append(self.mlp_encoder(mlp_in, **kwargs))
        return jnp.concatenate(feats, axis=-1)


class MultiDecoder(Module):
    """Per-key reconstruction heads over a latent: a deconv trunk whose output
    channels are split across image keys, and per-key MLP heads for vectors
    (/root/reference/sheeprl/models/models.py:463-489)."""

    cnn_decoder: Module | None
    mlp_decoder: Module | None
    mlp_heads: dict[str, Linear]
    cnn_keys: tuple[str, ...] = static(default=())
    mlp_keys: tuple[str, ...] = static(default=())
    cnn_channels: tuple[int, ...] = static(default=())

    def __call__(self, latent: jax.Array, **kwargs) -> dict:
        out: dict = {}
        if self.cnn_decoder is not None:
            img = self.cnn_decoder(latent)
            splits = jnp.split(img, jnp.cumsum(jnp.array(self.cnn_channels))[:-1], axis=-1)
            out.update(dict(zip(self.cnn_keys, splits)))
        if self.mlp_decoder is not None:
            trunk = self.mlp_decoder(latent, **kwargs)
            for k in self.mlp_keys:
                out[k] = self.mlp_heads[k](trunk)
        return out
