"""Primitive layers: Linear, Conv2d, ConvTranspose2d, LayerNorm, Dropout.

TPU-first choices:
  - convolutions run in NHWC with HWIO kernels — the layout the MXU tiles
    natively (no transposes inserted by XLA);
  - LayerNorm normalizes the trailing (channel) axis, so the reference's
    `LayerNormChannelLast` NCHW<->NLC shuffle
    (/root/reference/sheeprl/utils/model.py:225-235) disappears entirely;
  - params are float32 by default; forward math can be bf16 via Module.astype.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .core import Module, static

__all__ = ["Linear", "Conv2d", "ConvTranspose2d", "LayerNorm", "dropout"]


def _kaiming_uniform(key, shape, fan_in, dtype=jnp.float32):
    # torch's default Linear/Conv init — kaiming_uniform(a=sqrt(5)):
    # gain = sqrt(1/3), bound = gain * sqrt(3/fan_in) = 1/sqrt(fan_in)
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


class Linear(Module):
    weight: jax.Array  # [in_features, out_features]
    bias: jax.Array | None

    @classmethod
    def init(cls, key, in_features: int, out_features: int, *, use_bias: bool = True):
        wkey, bkey = jax.random.split(key)
        weight = _kaiming_uniform(wkey, (in_features, out_features), in_features)
        bias = None
        if use_bias:
            bound = 1.0 / math.sqrt(in_features)
            bias = jax.random.uniform(
                bkey, (out_features,), jnp.float32, minval=-bound, maxval=bound
            )
        return cls(weight=weight, bias=bias)

    def __call__(self, x: jax.Array) -> jax.Array:
        y = x @ self.weight.astype(x.dtype)
        if self.bias is not None:
            y = y + self.bias.astype(x.dtype)
        return y

    @property
    def in_features(self) -> int:
        return self.weight.shape[0]

    @property
    def out_features(self) -> int:
        return self.weight.shape[1]


def _conv_init_params(
    key,
    in_channels: int,
    out_channels: int,
    kernel_size: int | tuple[int, int],
    stride: int | tuple[int, int],
    padding: str | int | tuple[int, int],
    use_bias: bool,
):
    """Shared (transposed-)conv parameter construction: kernel/stride/padding
    normalization + torch-style kaiming-uniform init."""
    kh, kw = (kernel_size,) * 2 if isinstance(kernel_size, int) else kernel_size
    stride = (stride,) * 2 if isinstance(stride, int) else tuple(stride)
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    elif isinstance(padding, tuple) and isinstance(padding[0], int):
        padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    wkey, bkey = jax.random.split(key)
    fan_in = in_channels * kh * kw
    kernel = _kaiming_uniform(wkey, (kh, kw, in_channels, out_channels), fan_in)
    bias = None
    if use_bias:
        bound = 1.0 / math.sqrt(fan_in)
        bias = jax.random.uniform(
            bkey, (out_channels,), jnp.float32, minval=-bound, maxval=bound
        )
    return kernel, bias, stride, padding


class Conv2d(Module):
    """NHWC convolution with HWIO kernel."""

    kernel: jax.Array  # [kh, kw, in_ch, out_ch]
    bias: jax.Array | None
    stride: tuple[int, int] = static(default=(1, 1))
    padding: str | tuple[tuple[int, int], tuple[int, int]] = static(default="SAME")

    @classmethod
    def init(
        cls,
        key,
        in_channels: int,
        out_channels: int,
        kernel_size: int | tuple[int, int],
        *,
        stride: int | tuple[int, int] = 1,
        padding: str | int | tuple[int, int] = "SAME",
        use_bias: bool = True,
    ):
        kernel, bias, stride, padding = _conv_init_params(
            key, in_channels, out_channels, kernel_size, stride, padding, use_bias
        )
        return cls(kernel=kernel, bias=bias, stride=stride, padding=padding)

    def __call__(self, x: jax.Array) -> jax.Array:
        y = jax.lax.conv_general_dilated(
            x,
            self.kernel.astype(x.dtype),
            window_strides=self.stride,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.bias is not None:
            y = y + self.bias.astype(x.dtype)
        return y

    @property
    def in_channels(self) -> int:
        return self.kernel.shape[2]

    @property
    def out_channels(self) -> int:
        return self.kernel.shape[3]


class ConvTranspose2d(Module):
    """NHWC transposed convolution (fractionally-strided)."""

    kernel: jax.Array  # [kh, kw, in_ch, out_ch]
    bias: jax.Array | None
    stride: tuple[int, int] = static(default=(1, 1))
    padding: str | tuple[tuple[int, int], tuple[int, int]] = static(default="SAME")

    @classmethod
    def init(
        cls,
        key,
        in_channels: int,
        out_channels: int,
        kernel_size: int | tuple[int, int],
        *,
        stride: int | tuple[int, int] = 1,
        padding: str | int | tuple[int, int] = "SAME",
        use_bias: bool = True,
    ):
        kernel, bias, stride, padding = _conv_init_params(
            key, in_channels, out_channels, kernel_size, stride, padding, use_bias
        )
        return cls(kernel=kernel, bias=bias, stride=stride, padding=padding)

    def __call__(self, x: jax.Array) -> jax.Array:
        if (
            self.stride == (2, 2)
            and self.kernel.shape[:2] == (4, 4)
            and self.padding == "SAME"
        ):
            y = self._subpixel_k4s2(x)
        else:
            y = jax.lax.conv_transpose(
                x,
                self.kernel.astype(x.dtype),
                strides=self.stride,
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.bias is not None:
            y = y + self.bias.astype(x.dtype)
        return y

    def _subpixel_k4s2(self, x: jax.Array) -> jax.Array:
        """k4/s2/SAME transposed conv as ONE dense 2x2 conv + subpixel
        interleave (depth-to-space), instead of the fractionally-strided
        lowering that convolves a zero-dilated input (75% wasted MACs on the
        MXU for s=2). Output pixel (2i+dh, 2j+dw) only sees input pixels
        {i-1+dh..i+dh} x {j-1+dw..j+dw} through kernel taps of matching
        parity, so the 4x4 kernel regroups losslessly into four 2x2 phase
        kernels: K[a, b, (dh, dw)] = w[2a+dh, 2b+dw] (the Dreamer decoder
        stages are exactly this shape, reference agent.py:137-203)."""
        n, h, w, cin = x.shape
        k = self.kernel.astype(x.dtype)  # [4, 4, cin, cout]
        cout = k.shape[-1]
        kk = k.reshape(2, 2, 2, 2, cin, cout)  # [a, dh, b, dw, cin, cout]
        kk = kk.transpose(0, 2, 4, 1, 3, 5).reshape(2, 2, cin, 4 * cout)
        ph = jax.lax.conv_general_dilated(
            x,
            kk,
            window_strides=(1, 1),
            padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ).reshape(n, h + 1, w + 1, 2, 2, cout)
        row0 = jnp.stack([ph[:, :h, :w, 0, 0], ph[:, :h, 1:, 0, 1]], axis=3)
        row1 = jnp.stack([ph[:, 1:, :w, 1, 0], ph[:, 1:, 1:, 1, 1]], axis=3)
        return jnp.stack([row0, row1], axis=2).reshape(n, 2 * h, 2 * w, cout)


class LayerNorm(Module):
    """LayerNorm over the trailing axis (channels in NHWC / features)."""

    scale: jax.Array | None
    offset: jax.Array | None
    eps: float = static(default=1e-5)

    @classmethod
    def init(cls, dim: int, *, eps: float = 1e-5, elementwise_affine: bool = True):
        if elementwise_affine:
            return cls(scale=jnp.ones((dim,)), offset=jnp.zeros((dim,)), eps=eps)
        return cls(scale=None, offset=None, eps=eps)

    def __call__(self, x: jax.Array) -> jax.Array:
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.eps)
        if self.scale is not None:
            y = y * self.scale + self.offset
        return y.astype(x.dtype)


def dropout(key, x: jax.Array, rate: float, *, deterministic: bool = False):
    """Functional inverted dropout (pure — caller threads the key)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
