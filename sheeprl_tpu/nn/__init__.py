from .core import Activation, Module, activation, field, static
from .layers import Conv2d, ConvTranspose2d, LayerNorm, Linear, dropout
from .blocks import CNN, DeCNN, MLP, MultiDecoder, MultiEncoder, NatureCNN
from .recurrent import GRUCell, LayerNormGRUCell, LSTMCell, scan_cell
from .inits import init_kaiming_normal, init_orthogonal, map_layers

__all__ = [
    "Activation",
    "Module",
    "activation",
    "field",
    "static",
    "Linear",
    "Conv2d",
    "ConvTranspose2d",
    "LayerNorm",
    "dropout",
    "MLP",
    "CNN",
    "DeCNN",
    "NatureCNN",
    "MultiEncoder",
    "MultiDecoder",
    "GRUCell",
    "LayerNormGRUCell",
    "LSTMCell",
    "scan_cell",
    "init_orthogonal",
    "init_kaiming_normal",
    "map_layers",
]
