"""Weight-init transforms applied to already-built Module pytrees.

Functional equivalents of the reference's `.apply(init_fn)` passes:
  - `init_orthogonal`: orthogonal Linear weights + delta-orthogonal conv
    kernels, zero biases — SAC-AE's `weight_init`
    (/root/reference/sheeprl/algos/sac_ae/utils.py:75-87);
  - `init_kaiming_normal`: kaiming-normal Linear weights — PPO/SAC-family
    `init_weights` (/root/reference/sheeprl/utils/utils.py:89-103).

Each transform recursively rewrites every Linear / Conv2d / ConvTranspose2d
inside an arbitrary Module tree and returns a new tree (modules are frozen).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable

import jax
import jax.numpy as jnp

from .core import Module
from .layers import Conv2d, ConvTranspose2d, Linear

__all__ = ["init_orthogonal", "init_kaiming_normal", "init_xavier", "map_layers"]


def map_layers(
    module,
    key,
    fn: Callable[[Linear | Conv2d | ConvTranspose2d, jax.Array], Module],
):
    """Depth-first rewrite of every primitive layer in a Module tree. `fn`
    receives (layer, key) and returns the replacement layer; keys are
    fold_in-derived along the traversal so the pass is deterministic."""
    counter = [0]

    def next_key():
        counter[0] += 1
        return jax.random.fold_in(key, counter[0])

    def rec(obj):
        if isinstance(obj, (Linear, Conv2d, ConvTranspose2d)):
            return fn(obj, next_key())
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            changes = {
                f.name: rec(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
                if not f.metadata.get("static")
            }
            return dataclasses.replace(obj, **changes)
        if isinstance(obj, tuple):
            return tuple(rec(v) for v in obj)
        if isinstance(obj, list):
            return [rec(v) for v in obj]
        if isinstance(obj, dict):
            return {k: rec(v) for k, v in obj.items()}
        return obj

    return rec(module)


def _orthogonal(key, rows: int, cols: int, gain: float = 1.0) -> jax.Array:
    return jax.nn.initializers.orthogonal(scale=gain)(key, (rows, cols), jnp.float32)


def init_orthogonal(module, key):
    """Orthogonal Linear weights (+ zero bias) and delta-orthogonal conv
    kernels (https://arxiv.org/pdf/1806.05393.pdf): the kernel is zero except
    the center tap, which is an orthogonal matrix scaled by the relu gain —
    the reference `weight_init` (sac_ae/utils.py:75-87), which likewise has
    fixed gains (1 for Linear, sqrt(2) for convs)."""

    def rewrite(layer, k):
        if isinstance(layer, Linear):
            w = _orthogonal(k, layer.in_features, layer.out_features)
            b = None if layer.bias is None else jnp.zeros_like(layer.bias)
            return layer.replace(weight=w, bias=b)
        # conv kernels are HWIO
        kh, kw, cin, cout = layer.kernel.shape
        center = _orthogonal(k, cin, cout, gain=math.sqrt(2.0))
        kernel = jnp.zeros_like(layer.kernel).at[kh // 2, kw // 2].set(center)
        b = None if layer.bias is None else jnp.zeros_like(layer.bias)
        return layer.replace(kernel=kernel, bias=b)

    return map_layers(module, key, rewrite)


def init_xavier(module, key, mode: str = "normal"):
    """Xavier (Glorot) init of every Linear / Conv / ConvTranspose weight with
    zero biases — the Dreamer-family `init_weights`
    (/root/reference/sheeprl/algos/dreamer_v2/utils.py:41-60).
    `mode`: 'normal' | 'uniform' | 'zero' (the Hafner-initialization modes,
    /root/reference/sheeprl/algos/dreamer_v3/agent.py:1023-1033)."""
    if mode not in ("normal", "uniform", "zero"):
        raise ValueError(f"unknown xavier init mode {mode!r}")

    def rewrite(layer, k):
        if isinstance(layer, Linear):
            shape, fan_in, fan_out = (
                layer.weight.shape,
                layer.in_features,
                layer.out_features,
            )
            attr = "weight"
        else:
            # conv kernels are HWIO: fan counts include the receptive field
            kh, kw, cin, cout = layer.kernel.shape
            shape, fan_in, fan_out = layer.kernel.shape, cin * kh * kw, cout * kh * kw
            attr = "kernel"
        if mode == "zero":
            w = jnp.zeros(shape, jnp.float32)
        elif mode == "uniform":
            bound = math.sqrt(6.0 / (fan_in + fan_out))
            w = jax.random.uniform(k, shape, jnp.float32, minval=-bound, maxval=bound)
        else:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            w = std * jax.random.normal(k, shape, jnp.float32)
        b = None if layer.bias is None else jnp.zeros_like(layer.bias)
        return layer.replace(**{attr: w, "bias": b})

    return map_layers(module, key, rewrite)


def init_kaiming_normal(module, key):
    """Kaiming-normal (fan-in, relu gain) Linear weights, zero bias — the
    reference `init_weights` (utils/utils.py:89-103). Convs untouched."""

    def rewrite(layer, k):
        if not isinstance(layer, Linear):
            return layer
        std = math.sqrt(2.0 / layer.in_features)
        w = std * jax.random.normal(k, layer.weight.shape, jnp.float32)
        b = None if layer.bias is None else jnp.zeros_like(layer.bias)
        return layer.replace(weight=w, bias=b)

    return map_layers(module, key, rewrite)
