"""Pytree module system for the TPU-native framework.

Modules are frozen dataclasses registered as JAX pytrees: parameter arrays are
pytree leaves, configuration (sizes, activation names, ...) is static metadata.
A module therefore *is* its parameters — it can be passed straight through
`jax.jit`, `jax.grad`, `jax.lax.scan`, optax, and orbax without a separate
params dict. This replaces the reference's `torch.nn.Module` layer
(/root/reference/sheeprl/models/models.py) with a functional design that XLA
can trace once and compile.

Conventions:
  - construction happens in classmethod `init(key, ...)` factories so the
    dataclass `__init__` stays a plain field constructor (pytree unflatten
    needs that);
  - forward passes are `__call__(self, x, ...)` and must be pure;
  - images are NHWC (channels-last) — the native TPU conv layout.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp

__all__ = ["Module", "static", "field", "activation", "Activation", "cast_floating"]


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast every floating-point array leaf of `tree` to `dtype`, leaving
    integer/bool/uint8 leaves (and non-arrays) untouched.

    This is the one leaf-casting primitive of the mixed-precision policy
    (`ops/precision.py`): train steps cast their INPUTS to the compute
    dtype with it, heads cast their outputs back to the fp32 island, and
    `Module.astype` reuses it for whole-model inference casts. It is a
    no-op (returns the identical leaves, no `convert` in the jaxpr) when
    dtypes already match, so f32 runs trace byte-identical programs."""

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def static(default: Any = dataclasses.MISSING, **kwargs: Any) -> Any:
    """Declare a dataclass field as static pytree metadata (not a leaf)."""
    metadata = dict(kwargs.pop("metadata", {}) or {})
    metadata["static"] = True
    if default is dataclasses.MISSING:
        return dataclasses.field(metadata=metadata, **kwargs)
    return dataclasses.field(default=default, metadata=metadata, **kwargs)


def field(default: Any = dataclasses.MISSING, **kwargs: Any) -> Any:
    """Declare a regular (leaf) dataclass field."""
    if default is dataclasses.MISSING:
        return dataclasses.field(**kwargs)
    return dataclasses.field(default=default, **kwargs)


class Module:
    """Base class: subclassing turns the class into a frozen dataclass pytree."""

    def __init_subclass__(cls, **kwargs: Any) -> None:
        super().__init_subclass__(**kwargs)
        dataclasses.dataclass(frozen=True)(cls)
        fields = dataclasses.fields(cls)
        data = tuple(f.name for f in fields if not f.metadata.get("static"))
        meta = tuple(f.name for f in fields if f.metadata.get("static"))
        jax.tree_util.register_dataclass(cls, data_fields=data, meta_fields=meta)

    def replace(self, **changes: Any) -> "Module":
        return dataclasses.replace(self, **changes)

    # -- convenience ---------------------------------------------------------
    @property
    def n_params(self) -> int:
        return sum(
            x.size for x in jax.tree_util.tree_leaves(self) if hasattr(x, "size")
        )

    def astype(self, dtype: jnp.dtype) -> "Module":
        """Cast all floating-point leaves (e.g. to bfloat16 for inference).

        Training never uses this — the mixed-precision policy
        (`ops/precision.py`) keeps fp32 master params and casts
        activations instead (the layers follow their input dtype)."""
        return cast_floating(self, dtype)


# ---------------------------------------------------------------------------
# Activations are referenced by name so they can live in static metadata
# (callables in static fields would break pytree hashing across jit calls).
# ---------------------------------------------------------------------------

Activation = str | None

_ACTIVATIONS: dict[str, Callable[[jax.Array], jax.Array]] = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
    "softplus": jax.nn.softplus,
    "identity": lambda x: x,
}


def activation(name: Activation) -> Callable[[jax.Array], jax.Array]:
    """Resolve an activation name to its function (None -> identity)."""
    if name is None:
        return _ACTIVATIONS["identity"]
    try:
        return _ACTIVATIONS[name]
    except KeyError as e:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from e


def register_activation(name: str, fn: Callable[[jax.Array], jax.Array]) -> None:
    _ACTIVATIONS[name] = fn
